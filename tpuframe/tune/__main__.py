"""CLI for the offline autotuner.

    python -m tpuframe.tune sweep --topology v5e:2x2   # the whole thing
    python -m tpuframe.tune plan                        # spec planner
    python -m tpuframe.tune sweep --remat               # remat policy search
    python -m tpuframe.tune sweep --serve               # serving decode grid
    python -m tpuframe.tune sweep --zero1               # weight-update sharding
    python -m tpuframe.tune sweep --wire                # wire-format search
    python -m tpuframe.tune sweep --fusion              # fusion bucket grid
    python -m tpuframe.tune sweep --hier                # two-level collectives
    python -m tpuframe.tune show                        # ranked DB contents
    python -m tpuframe.tune check                       # CI self-check

Runs CPU-only: the sweep compiles against a compile-only TPU topology on
the CPU host (PERF.md §7) — no chip, no relay.  The env scrub below keeps
the axon TPU plugin from registering (it self-registers whenever
PALLAS_AXON_POOL_IPS is set) and forces real Mosaic lowering for pallas
kernels; it must run before jax initializes a backend.
"""

import argparse
import json
import os
import sys


def _ensure_cpu_env() -> None:
    """CPU-host env scrub (perf/_common.ensure_cpu_backend's rule).

    jax is imported by the tpuframe package root before this runs, but the
    backend is chosen lazily — re-exec is only needed when JAX_PLATFORMS
    was already forced to something other than cpu or the axon plugin
    would self-register.
    """
    os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    os.environ.setdefault("TPUFRAME_PALLAS_INTERPRET", "0")
    # Off-GCP hosts: libtpu's topology init otherwise polls the GCE
    # metadata server 30x per variable (~minutes of 403s) before giving up.
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    if (os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu")
            or os.environ.get("PALLAS_AXON_POOL_IPS", "")):
        print("[tune] re-exec on the plain CPU backend...", flush=True)
        os.environ.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        os.execvpe(sys.executable,
                   [sys.executable, "-m", "tpuframe.tune"] + sys.argv[1:],
                   os.environ)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _cmd_sweep(args) -> int:
    from tpuframe.tune import search

    if args.serve:
        search.serve_sweep(args.topology, db_path=args.db,
                           report_path=args.report,
                           blocks=tuple(args.serve_blocks),
                           slots_grid=tuple(args.serve_slots))
        return 0
    if args.remat:
        search.remat_sweep(args.topology, db_path=args.db,
                           report_path=args.report,
                           batch=args.remat_batch,
                           policies=tuple(args.remat_policies)
                           if args.remat_policies else None)
        return 0
    if args.zero1:
        search.zero1_sweep(args.topology, db_path=args.db,
                           report_path=args.report,
                           batch=args.zero1_batch)
        return 0
    if args.wire:
        search.wire_sweep(args.topology, db_path=args.db,
                          report_path=args.report,
                          batch=args.wire_batch)
        return 0
    if args.fusion:
        search.fusion_sweep(args.topology, db_path=args.db,
                            report_path=args.report,
                            batch=args.fusion_batch,
                            thresholds=tuple(args.fusion_thresholds))
        return 0
    if args.hier:
        search.hier_sweep(args.topology, slices=args.hier_slices,
                          db_path=args.db, report_path=args.report,
                          batch=args.hier_batch)
        return 0
    search.sweep(args.topology, db_path=args.db, report_path=args.report,
                 seq=args.seq, head_dim=args.head_dim,
                 blocks=tuple(args.blocks),
                 bench_batches=tuple(args.bench_batches))
    return 0


def _cmd_fusion_probe(args) -> int:
    import json

    from tpuframe.tune import search

    row = search._fusion_probe_row(args.topology, args.program,
                                   args.batch, args.threshold, args.floor)
    with open(args.out, "w") as f:
        json.dump(row, f)
    return 0


def _cmd_hier_probe(args) -> int:
    import json

    from tpuframe.tune import search

    payload = search._hier_probe_row(args.topology, args.slices,
                                     args.program, args.batch, args.mode,
                                     args.hier, args.wire_format_dcn)
    with open(args.out, "w") as f:
        json.dump(payload, f)
    return 0


def _cmd_plan(args) -> int:
    from tpuframe.tune import plan as plan_lib

    report = plan_lib.plan(args.topology,
                           slice_counts=tuple(args.slices),
                           db_path=args.db, report_path=args.report)
    return 0 if report.get("winner") else 1


def _cmd_show(args) -> int:
    from tpuframe.tune import db as tune_db

    path = args.db or tune_db.default_db_path()
    if not os.path.exists(path):
        print(f"no tuning DB at {path}")
        return 1
    db = tune_db.TuningDB.open(path)
    for fam in sorted({r.family for r in db.records()}):
        print(f"[{fam}]")
        for rec in db.top_k(10, family=fam):
            tier = ("measured" if rec.measured
                    and rec.measured.get("value") is not None
                    else "predicted")
            print(f"  {rec.program} {rec.generation} "
                  f"{json.dumps(rec.config, sort_keys=True)} "
                  f"-> {rec.predicted.get('predicted_ms')} ms "
                  f"({tier})")
    return 0


def _cmd_check(args) -> int:
    """Self-check the analysis gate registers: hardware-table sanity, DB
    schema validation, TF106 self-lint of the tuner's own flag plumbing."""
    from tpuframe.tune import check as run_check

    problems = run_check(db_path=args.db)
    for p in problems:
        print(f"[tune-check] {p}")
    print(f"[tune-check] {'FAIL' if problems else 'OK'}")
    return 1 if problems else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpuframe.tune",
                                 description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    sw = sub.add_parser("sweep", help="offline AOT sweep on a compile-only "
                                      "topology")
    sw.add_argument("--topology", default="v5e:2x2")
    sw.add_argument("--db", default=None, help="tuning DB path "
                    "(default: <repo>/tune_db.json)")
    sw.add_argument("--report", default=None)
    sw.add_argument("--seq", type=int, default=2048)
    sw.add_argument("--head-dim", type=int, default=64)
    sw.add_argument("--blocks", type=int, nargs="+",
                    default=[128, 256, 512])
    sw.add_argument("--bench-batches", type=int, nargs="+", default=[256])
    sw.add_argument("--serve", action="store_true",
                    help="sweep serving decode block sizes x slot counts "
                         "(serve_lm family) over the AOT decode step "
                         "instead of the fa/xla-opts grid")
    sw.add_argument("--serve-blocks", type=int, nargs="+",
                    default=[64, 128, 256], metavar="BLOCK")
    sw.add_argument("--serve-slots", type=int, nargs="+",
                    default=[8, 16], metavar="SLOTS")
    sw.add_argument("--remat", action="store_true",
                    help="sweep tpuframe.mem remat policies over the "
                         "donated ResNet-50 train step (bytes objective) "
                         "instead of the fa/xla-opts grid")
    sw.add_argument("--remat-batch", type=int, default=512)
    sw.add_argument("--zero1", action="store_true",
                    help="sweep weight-update sharding (replicated vs "
                         "ZeRO-1) over the donated ResNet-50 + BERT train "
                         "steps (weight_update_* families)")
    sw.add_argument("--zero1-batch", type=int, default=512)
    sw.add_argument("--wire", action="store_true",
                    help="sweep gradient-path wire formats (fp vs "
                         "int8-block quantized collectives) over the "
                         "donated ResNet-50 DP + BERT ZeRO-1 train steps "
                         "(wire_format_* families)")
    sw.add_argument("--wire-batch", type=int, default=512)
    sw.add_argument("--fusion", action="store_true",
                    help="sweep gradient-fusion bucket thresholds over "
                         "the donated ResNet-50 DP train step, ranked by "
                         "overlap score + compiled wire bytes "
                         "(fusion_threshold family)")
    sw.add_argument("--fusion-batch", type=int, default=512)
    sw.add_argument("--hier", action="store_true",
                    help="sweep two-level collectives on a compile-only "
                         "MULTI-slice topology (flat vs hier x fp vs "
                         "int8-block DCN leg), ranked on step + ICI + "
                         "DCN ms (hier_collectives family)")
    sw.add_argument("--hier-batch", type=int, default=512)
    sw.add_argument("--hier-slices", type=int, default=2,
                    help="slice count for the compile-only multi-slice "
                         "topology (PJRT num_slices)")
    sw.add_argument("--fusion-thresholds", type=int, nargs="+",
                    default=[16384, 32768, 65536, 131072, 262144],
                    metavar="BYTES")
    sw.add_argument("--remat-policies", nargs="+", default=None,
                    metavar="POLICY")
    sw.set_defaults(fn=_cmd_sweep)

    pl = sub.add_parser("plan", help="static auto-parallelism planner: "
                                     "enumerate specs, AOT-compile on a "
                                     "compile-only topology, gate on the "
                                     "shardflow detectors, rank by the "
                                     "cost stack")
    pl.add_argument("--topology", default="v5e:2x2")
    pl.add_argument("--slices", type=int, nargs="+", default=[1, 2],
                    help="slice counts to plan over (DCN hierarchy)")
    pl.add_argument("--db", default=None, help="tuning DB path "
                    "(default: <repo>/tune_db.json)")
    pl.add_argument("--report", default=None)
    pl.set_defaults(fn=_cmd_plan)

    # Hidden worker: one fusion candidate per process, because libtpu's
    # fusion emitter can SIGABRT on a bucket shape and the parent sweep
    # must survive to record the crash (fusion_sweep spawns these; the
    # parent holds the AOT lock, so the probe never takes it).
    fp = sub.add_parser("_fusion-probe")
    fp.add_argument("--topology", default="v5e:2x2")
    fp.add_argument("--program", default="resnet50")
    fp.add_argument("--batch", type=int, default=512)
    fp.add_argument("--floor", type=int, default=1024)
    fp.add_argument("--threshold", type=int, default=None)
    fp.add_argument("--out", required=True)
    fp.set_defaults(fn=_cmd_fusion_probe)

    # Hidden worker: one hier candidate per process — the compile-only
    # multi-slice backend wedges nondeterministically, and the parent
    # sweep must survive a timeout to retry/record it (hier_sweep
    # spawns these; the parent holds the AOT lock, the probe doesn't).
    hp = sub.add_parser("_hier-probe")
    hp.add_argument("--topology", default="v5e:2x2")
    hp.add_argument("--slices", type=int, default=2)
    hp.add_argument("--program", default="lm")
    hp.add_argument("--batch", type=int, default=512)
    hp.add_argument("--mode", default="replicated")
    hp.add_argument("--hier", default="flat")
    hp.add_argument("--wire-format-dcn", default="fp")
    hp.add_argument("--out", required=True)
    hp.set_defaults(fn=_cmd_hier_probe)

    sh = sub.add_parser("show", help="print ranked DB contents")
    sh.add_argument("--db", default=None)
    sh.set_defaults(fn=_cmd_show)

    ck = sub.add_parser("check", help="CI self-check (schema + tables + "
                                      "TF106 self-lint)")
    ck.add_argument("--db", default=None)
    ck.set_defaults(fn=_cmd_check)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    _ensure_cpu_env()
    sys.exit(main())
