"""tpuframe.tune — offline AOT autotuning (PERF.md §14).

Turns the ad-hoc perf/ census scripts into a first-class autotuner:

  - ``roofline``  — per-generation hardware tables + a scorer that converts
    a compiled program's cost/memory analysis into a predicted lower-bound
    ms/step, a binding-resource verdict, and a fits/OOM check.
  - ``search``    — candidate enumeration (flash-attention block grid pruned
    against the Mosaic VMEM double-buffer budget, ``TPUFRAME_XLA_OPTS``
    compiler-option sets, batch shapes) + the AOT sweep driver that compiles
    each candidate on a compile-only topology.
  - ``db``        — the persistent tuning database consulted by ``train.py``,
    ``bench.py`` and ``ops/flash_attention.py`` at startup.  Precedence:
    env override > measured > predicted > default.

``python -m tpuframe.tune sweep --topology v5e:2x2`` runs the whole thing
CPU-only — no TPU, no relay.

This package root is import-light on purpose: ``db``/``roofline`` are pure
stdlib so the flash-attention import-time lookup and the analysis-gate
self-check stay cheap; only ``search`` touches jax, and lazily.
"""

import os

from tpuframe.tune import db as db  # noqa: F401
from tpuframe.tune import roofline as roofline  # noqa: F401


def check(db_path: str | None = None) -> list:
    """The CI self-check (registered in the ``python -m tpuframe.analysis``
    gate and exposed as ``python -m tpuframe.tune check``): hardware-table
    sanity (the v5e roofline anchors must keep reproducing PERF.md §2),
    tuning-DB schema validation, and a TF106 self-lint of the tuner's own
    flag plumbing — the subsystem that hands out compiler options must not
    itself mutate XLA_FLAGS after backend init.  Returns problem strings;
    empty means healthy."""
    problems = list(roofline.check_tables())

    path = db_path or db.default_db_path()
    if os.path.exists(path):
        try:
            import json

            with open(path) as f:
                data = json.load(f)
            problems += [f"{os.path.basename(path)}: {p}"
                         for p in db.validate(data)]
        except Exception as e:  # noqa: BLE001
            problems.append(f"{path}: unreadable ({e})")

    from tpuframe.analysis import source_lint

    here = os.path.dirname(os.path.abspath(__file__))
    pkg = os.path.dirname(here)
    targets = [os.path.join(here, f) for f in sorted(os.listdir(here))
               if f.endswith(".py")]
    targets += [os.path.join(pkg, "utils", "xla_opts.py"),
                os.path.join(pkg, "utils", "compile_cache.py")]
    for target in targets:
        if not os.path.exists(target):
            problems.append(f"self-lint target missing: {target}")
            continue
        with open(target) as f:
            src = f.read()
        for finding in source_lint.lint_source(src, path=target):
            if finding.rule == "TF106":
                problems.append(f"self-lint {os.path.basename(target)}:"
                                f"{finding.line} {finding.message}")
    return problems
