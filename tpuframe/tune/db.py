"""Persistent tuning database (JSON), consulted at startup by ``train.py``,
``bench.py`` and ``ops/flash_attention.py``.

Records are keyed by (program, topology, generation, config) and carry a
two-tier score:

  predicted — written by the offline AOT sweep (``tpuframe.tune.search``):
              roofline lower-bound ms, binding resource, fits verdict,
              VMEM footprint for pallas candidates.  Compiler-measured,
              never chip-measured.
  measured  — written when a chip window opens and
              ``obs.autotune.replay_offline_topk`` re-runs the offline
              top-k through the real measured loop, upgrading the record.

Resolution precedence (docs/DESIGN.md "The tuning subsystem"):

    env override  >  measured  >  predicted  >  hard default

and DB resolution only engages when the target TPU generation is known
(``TPUFRAME_TUNE_GEN`` or ``PALLAS_AXON_TPU_GEN``) — a plain CPU test run
sees the hard defaults, untouched.

Pure stdlib; import-time cost is nil by design (flash_attention resolves
its block sizes through here at import).
"""

from __future__ import annotations

import hashlib
import json
import os

SCHEMA_VERSION = 1

# Env knobs.  TPUFRAME_TUNE_DB: path to the DB file; "", "0" or "off"
# disables DB resolution entirely.  TPUFRAME_TUNE_GEN: target generation
# for resolution when PALLAS_AXON_TPU_GEN (the relay's own hint) is unset.
_DB_ENV = "TPUFRAME_TUNE_DB"
_GEN_ENVS = ("TPUFRAME_TUNE_GEN", "PALLAS_AXON_TPU_GEN")
_OFF = ("", "0", "off", "none")

_REQUIRED_KEYS = ("program", "family", "fingerprint", "topology",
                  "generation", "config", "predicted")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_db_path() -> str:
    env = os.environ.get(_DB_ENV)
    if env and env.strip().lower() not in _OFF:
        return env
    return os.path.join(repo_root(), "tune_db.json")


def db_disabled() -> bool:
    env = os.environ.get(_DB_ENV)
    return env is not None and env.strip().lower() in _OFF


def target_generation() -> str | None:
    """The TPU generation runtime resolution should tune for, or None when
    unknown (-> callers keep their hard defaults; CPU test runs land
    here)."""
    for var in _GEN_ENVS:
        val = os.environ.get(var, "").strip().lower()
        if val:
            return val.split(":", 1)[0]
    return None


def fingerprint(desc, xla_opts: dict | None = None) -> str:
    """Stable program fingerprint: sha256 over the canonical JSON of a
    program description plus the (sorted) compiler-option set — so a seeded
    ``TPUFRAME_XLA_OPTS`` candidate yields a different fingerprint even
    when the lowered module text is identical (compiler options travel in
    the compile request, not the module)."""
    payload = {"desc": desc,
               "xla_opts": sorted((xla_opts or {}).items())}
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


class Record:
    """Thin read-mostly wrapper over one DB record dict."""

    def __init__(self, data: dict):
        self.data = data

    def __getitem__(self, k):
        return self.data[k]

    def get(self, k, default=None):
        return self.data.get(k, default)

    @property
    def program(self) -> str:
        return self.data["program"]

    @property
    def family(self) -> str:
        return self.data["family"]

    @property
    def generation(self) -> str:
        return self.data["generation"]

    @property
    def topology(self) -> str:
        return self.data["topology"]

    @property
    def config(self) -> dict:
        return self.data.get("config", {})

    @property
    def predicted(self) -> dict:
        return self.data.get("predicted", {})

    @property
    def measured(self) -> dict | None:
        return self.data.get("measured")

    def env_overrides(self) -> dict:
        """This record's config as the env vars the existing knobs read —
        the bridge into ``obs.autotune``'s subprocess measure loop."""
        env = {}
        cfg = self.config
        if "fa_block_q" in cfg:
            env["TPUFRAME_FA_BLOCK_Q"] = str(cfg["fa_block_q"])
        if "fa_block_k" in cfg:
            env["TPUFRAME_FA_BLOCK_K"] = str(cfg["fa_block_k"])
        if cfg.get("xla_opts"):
            env["TPUFRAME_XLA_OPTS"] = ",".join(
                f"{k}={v}" for k, v in sorted(cfg["xla_opts"].items()))
        if "batch" in cfg:
            env["TPUFRAME_BENCH_BATCH"] = str(cfg["batch"])
        if "remat_policy" in cfg:
            env["TPUFRAME_REMAT_POLICY"] = str(cfg["remat_policy"])
        if "weight_update" in cfg:
            env["TPUFRAME_WEIGHT_UPDATE"] = str(cfg["weight_update"])
        if "wire_format" in cfg:
            env["TPUFRAME_WIRE_FORMAT"] = str(cfg["wire_format"])
        if "wire_format_dcn" in cfg:
            env["TPUFRAME_WIRE_FORMAT_DCN"] = str(cfg["wire_format_dcn"])
        if "hier" in cfg:
            env["TPUFRAME_HIER"] = str(cfg["hier"])
        if "fusion_threshold" in cfg:
            env["TPUFRAME_FUSION_THRESHOLD"] = str(cfg["fusion_threshold"])
        if "spec" in cfg:
            env["TPUFRAME_SPEC"] = str(cfg["spec"])
        if "decode_block" in cfg:
            env["TPUFRAME_DECODE_BLOCK"] = str(cfg["decode_block"])
        if cfg.get("prompt_buckets"):
            env["TPUFRAME_SERVE_BUCKETS"] = ",".join(
                str(b) for b in cfg["prompt_buckets"])
        return env

    def _key(self):
        return (self.program, self.topology, self.generation,
                json.dumps(self.config, sort_keys=True))

    def _rank(self):
        """Sort key, best first.  Measured tier always beats predicted.
        Within measured: higher value wins when the measure maximizes
        (throughput — obs.autotune's convention), else lower.  Within
        predicted: lower roofline ms, then higher VMEM utilization — for
        pallas kernels cost_analysis cannot see inside the custom call
        (PERF.md §8), so roofline ms ties across block sizes and the
        fatter in-budget tiling (fewer grid steps, better pipelining) is
        the honest tiebreak."""
        m = self.measured
        if m and m.get("value") is not None:
            v = float(m["value"])
            return (0, -v if m.get("maximize", True) else v)
        p = self.predicted
        ms = p.get("predicted_ms")
        ms = float("inf") if ms is None else float(ms)
        return (1, ms, -float(p.get("vmem_bytes") or 0))


class TuningDB:
    def __init__(self, path: str, data: dict | None = None):
        self.path = path
        self.data = data or {"version": SCHEMA_VERSION, "records": []}

    @classmethod
    def open(cls, path: str | None = None) -> "TuningDB":
        path = path or default_db_path()
        data = None
        if os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
        db = cls(path, data)
        problems = validate(db.data)
        if problems:
            raise ValueError(f"tuning DB {path}: " + "; ".join(problems))
        return db

    def save(self, path: str | None = None) -> str:
        path = path or self.path
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.data, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path

    def records(self, *, program: str | None = None,
                family: str | None = None,
                generation: str | None = None,
                topology: str | None = None) -> list:
        out = []
        for raw in self.data.get("records", []):
            rec = Record(raw)
            if program is not None and rec.program != program:
                continue
            if family is not None and rec.family != family:
                continue
            if generation is not None and rec.generation != generation:
                continue
            if topology is not None and rec.topology != topology:
                continue
            out.append(rec)
        return out

    def add(self, record: dict) -> Record:
        """Insert or replace (same program/topology/generation/config key
        replaces — a re-sweep supersedes its own older predictions but
        never clobbers a different config's measured entry)."""
        missing = [k for k in _REQUIRED_KEYS if k not in record]
        if missing:
            raise ValueError(f"tuning record missing keys {missing}")
        rec = Record(record)
        kept = [r for r in self.data["records"]
                if Record(r)._key() != rec._key()]
        kept.append(record)
        self.data["records"] = kept
        return rec

    def top_k(self, k: int = 3, **filters) -> list:
        return sorted(self.records(**filters),
                      key=lambda r: r._rank())[:k]

    def best(self, **filters) -> Record | None:
        top = self.top_k(1, **filters)
        return top[0] if top else None

    def upgrade_measured(self, record: Record, value: float, *,
                         unit: str = "value", maximize: bool = True,
                         at: str | None = None) -> None:
        """Predicted -> measured upgrade in place (call save() after)."""
        for raw in self.data["records"]:
            if Record(raw)._key() == record._key():
                raw["measured"] = {"value": value, "unit": unit,
                                   "maximize": maximize}
                if at is not None:
                    raw["measured"]["at"] = at
                return
        raise KeyError(f"record not in DB: {record.program} "
                       f"{record.config}")

    def lookup(self, program: str, fp: str, **filters) -> Record | None:
        """Fingerprint-checked lookup: best record for ``program`` whose
        fingerprint matches ``fp``.  A mismatch (the program changed since
        the sweep) returns None — callers fall back to defaults rather
        than apply a stale tuning."""
        for rec in self.top_k(k=10 ** 6, program=program, **filters):
            if rec["fingerprint"] == fp:
                return rec
        return None


def validate(data) -> list:
    """Schema validation for the CI gate.  Returns problem strings."""
    problems = []
    if not isinstance(data, dict):
        return [f"DB root must be an object, got {type(data).__name__}"]
    if data.get("version") != SCHEMA_VERSION:
        problems.append(f"version {data.get('version')!r} != "
                        f"{SCHEMA_VERSION}")
    recs = data.get("records")
    if not isinstance(recs, list):
        return problems + ["'records' must be a list"]
    for i, raw in enumerate(recs):
        if not isinstance(raw, dict):
            problems.append(f"records[{i}]: not an object")
            continue
        missing = [k for k in _REQUIRED_KEYS if k not in raw]
        if missing:
            problems.append(f"records[{i}]: missing {missing}")
            continue
        if not isinstance(raw["config"], dict):
            problems.append(f"records[{i}]: config must be an object")
        pred = raw["predicted"]
        if not isinstance(pred, dict):
            problems.append(f"records[{i}]: predicted must be an object")
        m = raw.get("measured")
        if m is not None and (not isinstance(m, dict) or "value" not in m):
            problems.append(f"records[{i}]: measured needs a 'value'")
        gen = str(raw["generation"])
        from tpuframe.tune import roofline
        if gen.split(":", 1)[0] not in roofline.HARDWARE:
            problems.append(f"records[{i}]: unknown generation {gen!r}")
    return problems


def _open_for_resolution() -> TuningDB | None:
    if db_disabled():
        return None
    path = default_db_path()
    if not os.path.exists(path):
        return None
    try:
        return TuningDB.open(path)
    except Exception:  # noqa: BLE001 — a corrupt DB must never take down
        return None    # a training run; the analysis gate reports it.


def resolve_fa_blocks(default_q: int, default_k: int) -> tuple:
    """Flash-attention block sizes: env > measured > predicted > default.
    DB tiers only engage when the target generation is known — plain CPU
    runs (the whole fast test tier) see the hard defaults."""
    q, k = default_q, default_k
    gen = target_generation()
    if gen is not None:
        db = _open_for_resolution()
        if db is not None:
            rec = db.best(family="flash_attention", generation=gen)
            if rec is not None:
                q = int(rec.config.get("fa_block_q", q))
                k = int(rec.config.get("fa_block_k", k))
    env_q = os.environ.get("TPUFRAME_FA_BLOCK_Q")
    env_k = os.environ.get("TPUFRAME_FA_BLOCK_K")
    if env_q:
        q = int(env_q)
    if env_k:
        k = int(env_k)
    return q, k


def resolve_xla_opts(program: str, family: str | None = None) -> dict | None:
    """Compiler-option set for ``program``: None unless the DB has a tuned
    set for the target generation.  Callers apply ``TPUFRAME_XLA_OPTS``
    themselves FIRST (via utils.xla_opts.from_env) — when that env var is
    set this returns None so the override is unambiguous."""
    if os.environ.get("TPUFRAME_XLA_OPTS", "").strip():
        return None
    gen = target_generation()
    if gen is None:
        return None
    db = _open_for_resolution()
    if db is None:
        return None
    rec = db.best(program=program, generation=gen)
    if rec is None and family is not None:
        rec = db.best(family=family, generation=gen)
    if rec is None:
        return None
    opts = rec.config.get("xla_opts")
    return dict(opts) if opts else None


def resolve_remat_policy(program: str,
                         family: str | None = None) -> str | None:
    """Rematerialization policy for ``program``: None unless the DB has a
    swept winner for the target generation.  Callers apply
    ``TPUFRAME_REMAT_POLICY`` (and the legacy ``TPUFRAME_BENCH_REMAT``
    alias) themselves FIRST via :func:`tpuframe.mem.policy_from_env` —
    when either env var is set this returns None so the override is
    unambiguous."""
    if os.environ.get("TPUFRAME_REMAT_POLICY", "").strip():
        return None
    if os.environ.get("TPUFRAME_BENCH_REMAT", "").strip():
        return None
    gen = target_generation()
    if gen is None:
        return None
    db = _open_for_resolution()
    if db is None:
        return None
    rec = db.best(program=program, generation=gen)
    if (rec is None or "remat_policy" not in rec.config) \
            and family is not None:
        rec = db.best(family=family, generation=gen)
    if rec is None:
        return None
    pol = rec.config.get("remat_policy")
    return str(pol) if pol else None


def resolve_weight_update(program: str,
                          family: str | None = None) -> str | None:
    """Weight-update sharding mode for ``program``: None unless the DB has
    a swept ``weight_update_*`` winner for the target generation.  Callers
    apply ``TPUFRAME_WEIGHT_UPDATE`` themselves FIRST via
    :func:`tpuframe.parallel.zero1.resolve` — when the env var is set this
    returns None so the override is unambiguous."""
    if os.environ.get("TPUFRAME_WEIGHT_UPDATE", "").strip():
        return None
    gen = target_generation()
    if gen is None:
        return None
    db = _open_for_resolution()
    if db is None:
        return None
    rec = db.best(program=program, generation=gen)
    if (rec is None or "weight_update" not in rec.config) \
            and family is not None:
        rec = db.best(family=family, generation=gen)
    if rec is None:
        return None
    mode = rec.config.get("weight_update")
    return str(mode) if mode else None


def resolve_wire_format(program: str,
                        family: str | None = None) -> str | None:
    """Gradient-path collective wire format for ``program``: None unless
    the DB has a swept ``wire_format_*`` winner for the target
    generation.  Callers apply ``TPUFRAME_WIRE_FORMAT`` themselves FIRST
    via :func:`tpuframe.parallel.quantwire.resolve` — when the env var is
    set this returns None so the override is unambiguous."""
    if os.environ.get("TPUFRAME_WIRE_FORMAT", "").strip():
        return None
    gen = target_generation()
    if gen is None:
        return None
    db = _open_for_resolution()
    if db is None:
        return None
    rec = db.best(program=program, generation=gen)
    if (rec is None or "wire_format" not in rec.config) \
            and family is not None:
        rec = db.best(family=family, generation=gen)
    if rec is None:
        return None
    fmt = rec.config.get("wire_format")
    return str(fmt) if fmt else None


def resolve_wire_format_dcn(program: str,
                            family: str | None = None) -> str | None:
    """Wire format of the cross-slice (DCN) leg of the two-level
    lowering for ``program``: None unless the DB has a swept
    ``hier_collectives`` winner for the target generation.  Callers
    apply ``TPUFRAME_WIRE_FORMAT_DCN`` themselves FIRST via
    :func:`tpuframe.parallel.quantwire.resolve_legs` — when the env var
    is set this returns None so the override is unambiguous."""
    if os.environ.get("TPUFRAME_WIRE_FORMAT_DCN", "").strip():
        return None
    gen = target_generation()
    if gen is None:
        return None
    db = _open_for_resolution()
    if db is None:
        return None
    rec = db.best(program=program, generation=gen)
    if (rec is None or "wire_format_dcn" not in rec.config) \
            and family is not None:
        rec = db.best(family=family, generation=gen)
    if rec is None:
        return None
    fmt = rec.config.get("wire_format_dcn")
    return str(fmt) if fmt else None


def resolve_hier(program: str,
                 family: str | None = None) -> str | None:
    """Hierarchical-collective mode (flat/hier) for ``program``: None
    unless the DB has a swept ``hier_collectives`` winner for the target
    generation.  Callers apply ``TPUFRAME_HIER`` themselves FIRST via
    :func:`tpuframe.parallel.hier.resolve` — when the env var is set
    this returns None so the override is unambiguous."""
    if os.environ.get("TPUFRAME_HIER", "").strip():
        return None
    gen = target_generation()
    if gen is None:
        return None
    db = _open_for_resolution()
    if db is None:
        return None
    rec = db.best(program=program, generation=gen)
    if (rec is None or "hier" not in rec.config) and family is not None:
        rec = db.best(family=family, generation=gen)
    if rec is None:
        return None
    mode = rec.config.get("hier")
    return str(mode) if mode else None


def resolve_fusion_threshold(program: str,
                             family: str | None = None) -> int | None:
    """Gradient-fusion bucket threshold (bytes) for ``program``: None
    unless the DB has a swept ``fusion_threshold`` winner for the target
    generation.  Callers apply ``TPUFRAME_FUSION_THRESHOLD`` themselves
    FIRST via :func:`tpuframe.parallel.fusion.resolve` — when the env
    var is set this returns None so the override is unambiguous."""
    if os.environ.get("TPUFRAME_FUSION_THRESHOLD", "").strip():
        return None
    gen = target_generation()
    if gen is None:
        return None
    db = _open_for_resolution()
    if db is None:
        return None
    rec = db.best(program=program, generation=gen)
    if (rec is None or "fusion_threshold" not in rec.config) \
            and family is not None:
        rec = db.best(family=family, generation=gen)
    if rec is None:
        return None
    threshold = rec.config.get("fusion_threshold")
    try:
        return int(threshold) if threshold is not None else None
    except (TypeError, ValueError):
        return None


def resolve_spec(program: str,
                 family: str = "plan_spec") -> str | None:
    """Planned parallelism spec for ``program``: None unless the DB has a
    ``tune plan`` winner for the target generation.  Callers apply
    ``TPUFRAME_SPEC`` themselves FIRST via
    :func:`tpuframe.parallel.pspec.resolve` — when the env var is set (or
    an explicit spec argument was given) this returns None so the
    override is unambiguous.  Returns the canonical spec string the
    planner persisted (``config["spec"]``)."""
    if os.environ.get("TPUFRAME_SPEC", "").strip():
        return None
    gen = target_generation()
    if gen is None:
        return None
    db = _open_for_resolution()
    if db is None:
        return None
    rec = db.best(program=program, family=family, generation=gen)
    if rec is None:
        rec = db.best(family=family, generation=gen)
    if rec is None:
        return None
    spec = rec.config.get("spec")
    return str(spec) if spec else None


def resolve_decode_block(default: int = 128) -> int:
    """Serving KV-capacity granularity: env (``TPUFRAME_DECODE_BLOCK``)
    > tune-DB ``serve_lm`` winner > default.  Same generation gate as
    every other knob — plain CPU runs see the hard default."""
    block = default
    gen = target_generation()
    if gen is not None:
        db = _open_for_resolution()
        if db is not None:
            rec = db.best(family="serve_lm", generation=gen)
            if rec is not None and "decode_block" in rec.config:
                block = int(rec.config["decode_block"])
    env = os.environ.get("TPUFRAME_DECODE_BLOCK")
    if env and env.strip():
        block = int(env)
    return block


def resolve_serve_buckets(default: tuple) -> tuple:
    """Serving prompt-length buckets: env (``TPUFRAME_SERVE_BUCKETS``,
    comma-separated) > tune-DB ``serve_lm`` winner > default."""
    buckets = tuple(default)
    gen = target_generation()
    if gen is not None:
        db = _open_for_resolution()
        if db is not None:
            rec = db.best(family="serve_lm", generation=gen)
            if rec is not None and rec.config.get("prompt_buckets"):
                buckets = tuple(int(b)
                                for b in rec.config["prompt_buckets"])
    env = os.environ.get("TPUFRAME_SERVE_BUCKETS")
    if env and env.strip():
        from tpuframe.serve.kv_cache import parse_buckets
        buckets = parse_buckets(env)
    return buckets
