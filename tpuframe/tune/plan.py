"""Static auto-parallelism planner — ``python -m tpuframe.tune plan``.

Closes ROADMAP's "turn strategy choice into a static analysis pass":
enumerate the valid ``tpuframe.parallel.pspec`` layouts for a model ×
device count × slice count, AOT-compile each on a compile-only TPU
topology (no chip, no relay — the PERF §7 trick), run every shardflow
structural detector as an ADMISSIBILITY gate, and rank the survivors by
the analysis-v3 cost stack:

  - roofline compute/HBM verdict of the compiled step
    (``roofline.score_compiled`` — flops and bytes from cost_analysis),
  - the ICI/DCN comm split priced by fabric
    (``shardflow.comm_split`` -> ``roofline.comm_split_score``; a
    collective whose replica groups span slices pays DCN bandwidth),
  - overlap potential (how much of the wire time is hideable under
    legally-interleavable compute),
  - liveness peak-HBM vs the generation's capacity (``fits``).

The objective is ``predicted_total_ms = step + t_ici + t_dcn`` — the
same step-plus-wire objective the §20 wire sweep ranks on, extended
with the DCN column.  The winner is persisted to ``tune_db.json``
(family ``plan_spec``) under the standard env > DB > default
resolution, so ``train.py`` consumes a planned spec unless
``TPUFRAME_SPEC`` overrides it.

The pinned, schema-versioned report (``perf/results/plan_report_*``)
plus :func:`check`'s seeded ranking-drift positive make the planner a
gate leg, not a demo: the checked-in ranking must be re-derivable from
the checked-in rows, and the report must statically reproduce the
pinned PERF verdicts (§18 replicated-vs-zero1 bytes, §20 fp-vs-int8
totals, §23 DCN dominance on the composed spec) from cost models alone.

Everything here is CPU-host only; jax is imported lazily (:func:`check`
runs in the analysis gate, which must stay cheap when the report is
merely validated, not regenerated).
"""

from __future__ import annotations

import copy
import json
import os

from tpuframe.tune import db as tune_db
from tpuframe.tune import roofline

#: Schema of the plan report — bump on any row/verdict shape change.
PLAN_SCHEMA = 1

#: The DB family the winner lands in (``db.resolve_spec`` reads it).
PLAN_FAMILY = "plan_spec"

#: The program tag planned specs are recorded under.
PLAN_PROGRAM = "train_lm_tiny"


def _log(msg, log=None):
    (log or (lambda m: print(f"[plan] {m}", flush=True)))(msg)


def default_report_path(topology: str = "v5e:2x2") -> str:
    tag = topology.replace(":", "_").replace("x", "")
    return os.path.join(tune_db.repo_root(), "perf", "results",
                        f"plan_report_{tag}.json")


def _scaled_topology(topology: str, n_slices: int) -> str:
    """The compile topology for an ``n_slices``-slice candidate leg.

    Multi-slice candidates compile on a SINGLE-slice topology carrying
    the total chip count (``v5e:2x2`` x 2 slices -> ``v5e:2x4``), with
    the ``slice`` axis declared logically in the mesh — the same
    methodology as the §23 pin ("the slices are logical on this host").
    A real ``num_slices>1`` compile-only topology lowers collectives
    into per-slice partition IDs (2 replicas x 4 partitions whose
    replica groups cover ``[0..3]`` twice), which the static
    replica-group plane cannot attribute against the declared 8-device
    mesh; the logical form keeps every group materializable and the
    ICI/DCN split exact — ``comm_split`` still prices any collective
    whose groups cross the declared slice boundary at DCN bandwidth."""
    if n_slices <= 1:
        return topology
    base, _, dims = topology.partition(":")
    parts = dims.split("x")
    parts[-1] = str(int(parts[-1]) * n_slices)
    return f"{base}:{'x'.join(parts)}"


def enumerate_candidates(n_devices: int, n_slices: int = 1) -> list:
    """The candidate grid for one (world size, slice count).

    Specs are written with the ``dp=*`` wildcard so one grid serves any
    world size; degrees that cannot fit ``n_devices`` are recorded as
    skips by the sweep (the spec is for a different world), never
    silently dropped.  Modifier candidates (zero1 / int8-block / adasum /
    bucketed fusion) ride the plain-dp spec — they are step modifiers,
    not mesh axes."""
    tail = f";slices={n_slices}" if n_slices > 1 else ""
    cands = [
        {"spec": "dp=*" + tail},
        {"spec": "dp=*" + tail, "weight_update": "zero1"},
        {"spec": "dp=*" + tail, "wire_format": "int8-block"},
        {"spec": "dp=*" + tail, "weight_update": "zero1",
         "wire_format": "int8-block"},
        {"spec": "dp=*" + tail, "grad_reduce": "adasum"},
        # Bucketed-fusion variants: the staged overlapped gradient pass
        # at the registry threshold (strategies._FUSED_REGISTRY_THRESHOLD
        # — 128 KiB).  audit_spec signs declared_overlapped for them, so
        # an inadmissible (all-exposed) lowering is gated out here, not
        # just reported.
        {"spec": "dp=*" + tail, "fusion_threshold": 131072},
        {"spec": "dp=*" + tail, "weight_update": "zero1",
         "fusion_threshold": 131072},
        {"spec": "dp=*,fsdp=2" + tail},
        {"spec": "dp=*,tp=2" + tail},
        {"spec": "dp=*,tp=4" + tail},
        {"spec": "dp=*,ep=2" + tail},
        {"spec": "dp=*,sp=2" + tail, "seq_mode": "ring"},
        {"spec": "dp=*,sp=2" + tail, "seq_mode": "ulysses"},
        {"spec": "dp=*,pp=2" + tail},
    ]
    if n_slices > 1:
        # The §23 composed acceptance spec: dp×fsdp inside each slice,
        # replicated over the DCN slice axis.
        cands.append({"spec": f"dp=2,fsdp=2;slices={n_slices}"})
        # §28 two-level candidates: the hierarchical lowering (in-slice
        # reduce-scatter → cross-slice exchange of 1/n_inner → in-slice
        # all-gather) and its int8-block DCN leg, alone and composed
        # with ZeRO-1.  Only meaningful with a slice axis to cross.
        cands.append({"spec": "dp=*" + tail, "hier": "hier"})
        cands.append({"spec": "dp=*" + tail, "hier": "hier",
                      "wire_format_dcn": "int8-block"})
        cands.append({"spec": "dp=*" + tail, "weight_update": "zero1",
                      "hier": "hier"})
        cands.append({"spec": "dp=*" + tail, "weight_update": "zero1",
                      "hier": "hier", "wire_format_dcn": "int8-block"})
    return cands


def _admissible(row: dict) -> bool:
    return row.get("status") == "ok" and row.get("fits") is not False


def rank_rows(rows: list) -> list:
    """Deterministic ranking over admissible rows: lower predicted total
    (step + ICI + DCN) wins, fewer wire bytes breaks ties, name is the
    final total order.  Returns the ranked name list — re-derivable from
    the report's own rows, which is what :func:`check` pins."""
    adm = [r for r in rows if _admissible(r)]
    adm.sort(key=lambda r: (r.get("predicted_total_ms") or float("inf"),
                            r.get("comm_bytes") or 0, r["name"]))
    return [r["name"] for r in adm]


def _row(rows: list, name: str) -> dict | None:
    for r in rows:
        if r["name"] == name:
            return r
    return None


def compute_verdicts(rows: list) -> dict:
    """Re-derive the four pinned PERF verdicts from the candidate rows.

    Pure arithmetic over the report — no jax, no recompile — so the
    gate can re-check them against the stored booleans forever.  Each
    verdict carries the numbers it compared; ``holds`` is whether the
    pinned PERF direction reproduced.  A verdict whose required rows
    are missing (capability skip) reports ``holds: None``."""
    verdicts = {}

    dp = _row(rows, "spec:dp=*")
    zero1 = _row(rows, "spec:dp=*+zero1")
    v = {"perf_section": 18,
         "claim": "replicated dp moves fewer wire bytes than ZeRO-1 "
                  "(rs+ag ~ 2x the all-reduce) — zero1 is a capacity "
                  "lever, not a bytes one"}
    if dp and zero1:
        v.update(dp_comm_bytes=dp["comm_bytes"],
                 zero1_comm_bytes=zero1["comm_bytes"],
                 holds=dp["comm_bytes"] < zero1["comm_bytes"])
    else:
        v["holds"] = None
    verdicts["zero1_bytes"] = v

    fp = _row(rows, "spec:dp=*")
    int8 = _row(rows, "spec:dp=*+int8-block")
    v = {"perf_section": 20,
         "claim": "at this scale the fp wire beats int8-block on the "
                  "step+wire total: the quantize arithmetic lands in "
                  "the step roofline and costs more than the saved "
                  "bytes — the totals decide, the bytes alone do not"}
    if fp and int8:
        ratio = (int8["comm_bytes"] / fp["comm_bytes"]
                 if fp["comm_bytes"] else None)
        v.update(fp_total_ms=fp["predicted_total_ms"],
                 int8_total_ms=int8["predicted_total_ms"],
                 fp_comm_bytes=fp["comm_bytes"],
                 int8_comm_bytes=int8["comm_bytes"],
                 wire_bytes_ratio=round(ratio, 3) if ratio else None,
                 holds=(fp["predicted_total_ms"]
                        < int8["predicted_total_ms"]))
    else:
        v["holds"] = None
    verdicts["wire_bytes"] = v

    composed = None
    for r in rows:
        if r.get("slices", 1) > 1 and r["spec"].startswith("dp=2,fsdp=2"):
            composed = r
            break
    v = {"perf_section": 23,
         "claim": "on the composed dp×fsdp;slices=2 spec the DCN hop "
                  "dominates the wire clock despite carrying fewer "
                  "bytes than ICI (the ~32x bandwidth gap)"}
    if composed and _admissible(composed):
        v.update(ici_bytes=composed["ici_bytes"],
                 dcn_bytes=composed["dcn_bytes"],
                 t_ici_ms=composed["t_ici_ms"],
                 t_dcn_ms=composed["t_dcn_ms"],
                 holds=(composed["t_dcn_ms"] > composed["t_ici_ms"]
                        and composed["dcn_bytes"] < composed["ici_bytes"]))
    else:
        v["holds"] = None
    verdicts["dcn_split"] = v

    flat2 = _row(rows, "spec:dp=*;slices=2")
    hier2 = _row(rows, "spec:dp=*;slices=2+hier")
    hier_i8 = _row(rows, "spec:dp=*;slices=2+hier+dcn-int8")
    v = {"perf_section": 28,
         "claim": "the two-level lowering crushes the DCN term: +hier "
                  "moves <= 1/n_inner of the flat cross-slice bytes "
                  "over DCN (t_dcn follows), and the int8-block DCN "
                  "leg cuts strictly deeper"}
    if flat2 and hier2 and flat2.get("dcn_bytes"):
        ratio = hier2["dcn_bytes"] / flat2["dcn_bytes"]
        holds = ratio <= 0.5 and hier2["t_dcn_ms"] < flat2["t_dcn_ms"]
        v.update(flat_dcn_bytes=flat2["dcn_bytes"],
                 hier_dcn_bytes=hier2["dcn_bytes"],
                 dcn_bytes_ratio=round(ratio, 4),
                 flat_t_dcn_ms=flat2["t_dcn_ms"],
                 hier_t_dcn_ms=hier2["t_dcn_ms"])
        if hier_i8:
            r8 = hier_i8["dcn_bytes"] / flat2["dcn_bytes"]
            v.update(int8_dcn_bytes=hier_i8["dcn_bytes"],
                     int8_dcn_bytes_ratio=round(r8, 4))
            holds = holds and r8 < ratio
        v["holds"] = holds
    else:
        v["holds"] = None
    verdicts["hier_dcn"] = v
    return verdicts


def plan(topology: str = "v5e:2x2", *, slice_counts=(1, 2),
         db_path: str | None = None, report_path: str | None = None,
         log=None) -> dict:
    """Run the planner: enumerate, compile, gate, rank, persist."""
    import jax  # noqa: F401 — fail fast before holding the lock

    from tpuframe.analysis import shardflow, strategies
    from tpuframe.parallel import pspec
    from tpuframe.tune import search

    search.hold_aot_lock()
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    gen = roofline.generation_from_topology(topology)
    hw = roofline.get_hardware(gen)

    rows: list = []
    skips: list = []
    for n_slices in slice_counts:
        compile_topo = _scaled_topology(topology, n_slices)
        try:
            devices = pspec.topology_devices(compile_topo, slices=1)
        except Exception as e:  # noqa: BLE001 — this jax may lack
            skips.append({"slices": n_slices,       # the scaled shape
                          "topology": compile_topo,
                          "reason": f"{type(e).__name__}: {e}"[:300]})
            _log(f"slices={n_slices}: topology {compile_topo} "
                 f"unavailable ({type(e).__name__})", log)
            continue
        n = len(devices)
        _log(f"slices={n_slices}: {n} compile-only devices "
             f"({compile_topo}, slice axis logical)", log)
        for cand in enumerate_candidates(n, n_slices):
            audit = strategies.audit_spec(
                cand["spec"], n_devices=n, devices=devices,
                weight_update=cand.get("weight_update", "replicated"),
                wire_format=cand.get("wire_format"),
                seq_mode=cand.get("seq_mode"),
                grad_reduce=cand.get("grad_reduce"),
                fusion_threshold=cand.get("fusion_threshold"),
                hier=cand.get("hier"),
                wire_format_dcn=cand.get("wire_format_dcn"))
            base = {"name": audit.name, "spec": cand["spec"],
                    "slices": n_slices, "n_devices": n,
                    "compile_topology": compile_topo,
                    "config": {k: v for k, v in cand.items()
                               if k != "spec"}}
            if audit.status == "unavailable":
                base.update(status="skip", reason=audit.reason[:300])
                skips.append(base)
                _log(f"  {audit.name}: SKIP ({audit.reason[:70]})", log)
                continue
            flow = shardflow.audit_flow(audit, n_devices=n, drift=False)
            # Admissibility is the STRUCTURAL shardflow plane (redundant
            # pairs, wire dtypes, replication, replica groups, census,
            # exposed comm).  The analytic CommBudget classes stay
            # informational: they pin wire *patterns* to the registry's
            # 8-CPU-device world, and the TPU backend legitimately
            # lowers the same program differently at other world sizes
            # (e.g. ZeRO-1 at n=4 becomes all-reduce + per-param
            # all-gathers, which the class forbids) — that drift is a
            # planner finding, not an inadmissible layout.
            problems = list(flow["problems"])
            pred = roofline.score_compiled(audit.compiled, gen)
            split = roofline.comm_split_score(
                gen, flow["comm_split"], n_devices=n, n_slices=n_slices)
            # unrounded step roofline — the tiny model's differences
            # live below score()'s 2-decimal rounding
            t_step = max(pred["flops"] / hw.bf16_flops,
                         pred["bytes"] / hw.hbm_bytes_per_s) * 1e3
            total = t_step + split["t_ici_ms"] + split["t_dcn_ms"]
            base.update(
                status="ok" if not problems else "inadmissible",
                detector_problems=problems,
                budget_findings=list(audit.violations),
                predicted_step_ms=round(t_step, 6),
                t_ici_ms=split["t_ici_ms"],
                t_dcn_ms=split["t_dcn_ms"],
                ici_bytes=split["ici_bytes"],
                dcn_bytes=split["dcn_bytes"],
                comm_bytes=split["ici_bytes"] + split["dcn_bytes"],
                predicted_total_ms=round(total, 6),
                overlap_potential=flow["overlap"]["overlap_potential"],
                bound=pred["bound"], fits=pred["fits"],
                peak_memory_bytes=pred["peak_memory_bytes"])
            rows.append(base)
            _log(f"  {audit.name}: {base['status']} "
                 f"total {base['predicted_total_ms']:.4f} ms "
                 f"({base['comm_bytes']} wire B, "
                 f"ici {base['t_ici_ms']} / dcn {base['t_dcn_ms']} ms)",
                 log)

    ranking = rank_rows(rows)
    report = {
        "schema": PLAN_SCHEMA,
        "jax": _jax_version(),
        "topology": topology,
        "generation": gen,
        "objective": "predicted_step_ms + t_ici_ms + t_dcn_ms "
                     "(roofline step + comm split priced per fabric)",
        "slice_counts": list(slice_counts),
        "candidates": rows,
        "skips": skips,
        "ranking": ranking,
        "winner": _row(rows, ranking[0]) if ranking else None,
        "verdicts": compute_verdicts(rows),
    }

    if report["winner"] is not None:
        db_path = db_path or tune_db.default_db_path()
        db = tune_db.TuningDB.open(db_path) if os.path.exists(db_path) \
            else tune_db.TuningDB(db_path)
        win = report["winner"]
        canonical = pspec.parse_spec(win["spec"]).canonical()
        desc = {"program": PLAN_PROGRAM, "planner": "tune.plan",
                "spec": canonical, "config": win["config"],
                "slices": win["slices"], "n_devices": win["n_devices"]}
        db.add({"program": PLAN_PROGRAM, "family": PLAN_FAMILY,
                "fingerprint": tune_db.fingerprint(desc),
                "topology": topology, "generation": gen,
                "config": dict(win["config"], spec=canonical),
                "predicted": {
                    "predicted_ms": win["predicted_total_ms"],
                    "comm_bytes": win["comm_bytes"],
                    "overlap_potential": win["overlap_potential"],
                    "source": "planned"}})
        db.save()
        _log(f"winner {win['name']} -> {db.path} "
             f"(family {PLAN_FAMILY})", log)

    report_path = report_path or default_report_path(topology)
    os.makedirs(os.path.dirname(report_path), exist_ok=True)
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    _log(f"report: {report_path} ({len(rows)} scored, "
         f"{len(skips)} skipped, winner "
         f"{ranking[0] if ranking else 'none'})", log)
    return report


def _jax_version() -> str:
    import jax

    return jax.__version__


# ---------------------------------------------------------------------------
# Gate self-check: schema pin + re-derivable ranking + seeded
# ranking-drift positive + the pinned-verdict smoke.  Pure JSON over the
# checked-in report — jax is touched only for the version stamp.
# ---------------------------------------------------------------------------

_REQUIRED_REPORT_KEYS = ("schema", "jax", "topology", "generation",
                         "candidates", "skips", "ranking", "winner",
                         "verdicts")

_REQUIRED_ROW_KEYS = ("name", "spec", "slices", "n_devices", "status",
                      "detector_problems", "budget_findings",
                      "predicted_step_ms",
                      "t_ici_ms", "t_dcn_ms", "ici_bytes", "dcn_bytes",
                      "comm_bytes", "predicted_total_ms",
                      "overlap_potential", "bound", "fits")


def _schema_problems(report: dict) -> list:
    problems = []
    if report.get("schema") != PLAN_SCHEMA:
        problems.append(f"plan report schema {report.get('schema')!r} != "
                        f"pinned {PLAN_SCHEMA}")
        return problems
    for k in _REQUIRED_REPORT_KEYS:
        if k not in report:
            problems.append(f"plan report missing key {k!r}")
    for row in report.get("candidates", []):
        for k in _REQUIRED_ROW_KEYS:
            if k not in row:
                problems.append(f"plan row {row.get('name')!r} missing "
                                f"key {k!r}")
                break
    return problems


def _ranking_problems(report: dict) -> list:
    """The checked-in ranking must be re-derivable from the checked-in
    rows, every ranked candidate must be detector-clean, and the winner
    must be the top of the ranking."""
    problems = []
    rows = report.get("candidates", [])
    ranking = report.get("ranking", [])
    derived = rank_rows(rows)
    if derived != ranking:
        problems.append(f"plan ranking drift: report pins {ranking[:4]}"
                        f"..., rows re-rank to {derived[:4]}...")
    for name in ranking:
        row = _row(rows, name)
        if row is None:
            problems.append(f"plan ranking names unknown row {name!r}")
        elif row.get("detector_problems"):
            problems.append(
                f"plan ranked candidate {name!r} carries detector "
                f"findings — admissibility gate leaked: "
                f"{row['detector_problems'][:2]}")
    winner = report.get("winner")
    if ranking and (not winner or winner.get("name") != ranking[0]):
        problems.append(f"plan winner {winner and winner.get('name')!r} "
                        f"is not the ranking head {ranking[0]!r}")
    return problems


def _seeded_ranking_positive(report: dict) -> list:
    """Corrupt a copy of the rows (swap the top two candidates' totals)
    and require the ranking validator to notice — a validator that
    cannot see a swapped ranking is blind, and the gate refuses to run
    blind (the shardflow seeded-positive idiom)."""
    rows = copy.deepcopy(report.get("candidates", []))
    ranking = report.get("ranking", [])
    if len(ranking) < 2:
        return ["plan seeded positive: fewer than 2 admissible "
                "candidates — the ranking cannot be cross-checked"]
    a, b = _row(rows, ranking[0]), _row(rows, ranking[-1])
    a["predicted_total_ms"], b["predicted_total_ms"] = (
        b["predicted_total_ms"], a["predicted_total_ms"])
    a["comm_bytes"], b["comm_bytes"] = b["comm_bytes"], a["comm_bytes"]
    if rank_rows(rows) == ranking:
        return ["plan seeded positive: swapping the best and worst "
                "candidates' costs did not change the derived ranking — "
                "the ranking-drift detector is blind"]
    return []


def _verdict_problems(report: dict) -> list:
    """The pinned PERF verdicts must re-derive from the rows AND hold.
    A verdict that stopped holding is a real finding (the cost stack or
    the programs moved); a verdict whose stored booleans disagree with
    the rows is a tampered report."""
    problems = []
    rows = report.get("candidates", [])
    stored = report.get("verdicts", {})
    fresh = compute_verdicts(rows)
    for key, want in fresh.items():
        got = stored.get(key)
        if got is None:
            problems.append(f"plan verdict {key!r} missing from report")
            continue
        if got.get("holds") != want.get("holds"):
            problems.append(
                f"plan verdict {key!r} stored holds={got.get('holds')} "
                f"but rows re-derive holds={want.get('holds')} — report "
                f"and rows disagree")
        if want.get("holds") is False:
            problems.append(
                f"plan verdict {key!r} (PERF §{want.get('perf_section')}) "
                f"does NOT hold on the pinned rows — the planner "
                f"contradicts the pinned PERF verdict")
    return problems


def check(report_path: str | None = None) -> list:
    """Gate leg: validate the pinned plan report.  Version-skew skip
    follows ``--emit-budgets``: a report emitted by another jax is not a
    finding (its compiled programs are pinned to that emitter), so the
    check returns clean and the regenerate path re-pins."""
    path = report_path or default_report_path()
    if not os.path.exists(path):
        return [f"plan report missing: {path} — run "
                f"`python -m tpuframe.tune plan`"]
    try:
        with open(path) as f:
            report = json.load(f)
    except Exception as e:  # noqa: BLE001
        return [f"plan report unreadable: {path} ({e})"]
    problems = _schema_problems(report)
    if problems:
        return problems
    try:
        if report.get("jax") != _jax_version():
            return []  # pinned to the emitting jax — skip, not a finding
    except Exception:  # noqa: BLE001 — no jax here means pure-JSON mode
        pass
    problems += _ranking_problems(report)
    problems += _seeded_ranking_positive(report)
    problems += _verdict_problems(report)
    return problems
