"""Roofline scorer: compiled-program cost/memory analysis -> predicted step
time lower bound, binding-resource verdict, and a fits/OOM check.

Per-generation hardware tables.  The v5e numbers are the ones every PERF.md
roofline uses (197 TFLOPs bf16, 0.81 TB/s HBM, 15.75 GB usable HBM) and the
v4/v5p/v6e peak-flops column matches bench.py's ``BF16_PEAK_FLOPS`` table so
the two can never disagree on MFU.

Honesty caveats carried from PERF.md:

  - §8: XLA's ``cost_analysis`` counts a ``lax.scan`` body ONCE per program
    (not once per iteration) and cannot see inside a pallas custom call, so
    flop/byte totals of scan-containing programs are LOWER BOUNDS.  Scores
    for such programs are tagged ``bytes_lower_bound=True``; temp/argument
    memory and the fits verdict are exact either way.
  - §7.4a: the roofline is a LOWER bound on step time — the measured
    ResNet-50 step sits at ~81% of the HBM roofline (scheduling gap), so a
    predicted 177 ms means "not faster than 177 ms", never "177 ms".

Pure stdlib — no jax import.  ``score_compiled`` takes the compiled object
duck-typed (anything with ``cost_analysis``/``memory_analysis``/``as_text``).
"""

from __future__ import annotations

import dataclasses

GiB = 1024 ** 3


@dataclasses.dataclass(frozen=True)
class Hardware:
    """Per-chip peaks for one TPU generation."""

    generation: str
    bf16_flops: float      # peak bf16 FLOPs/s (MXU)
    hbm_bytes_per_s: float  # peak HBM bandwidth, bytes/s
    hbm_capacity_bytes: float  # usable HBM per chip, bytes
    ici_bytes_per_s: float = 0.0  # aggregate ICI bandwidth per chip, bytes/s
    dcn_bytes_per_s: float = 0.0  # per-chip DCN share for cross-slice hops


# Sources: v5e column = PERF.md §2 (197e12 / 0.81e12 / 15.75 GB, the values
# every recorded roofline in this repo was computed against).  Peak-flops
# column for v4/v5p/v6e = bench.py BF16_PEAK_FLOPS.  v4 HBM = 1.23 TB/s /
# 32 GB, v5p = 2.76 TB/s / 95 GB, v6e = 1.64 TB/s / 32 GB (public TPU
# system specs; only the v5e row is pinned by recorded measurements here).
# ICI column: aggregate interchip bandwidth per chip from the same public
# specs — v4 2400 Gbps, v5e 1600 Gbps, v5p 4800 Gbps, v6e 3584 Gbps.
# DCN column: a cross-slice collective leaves the ICI torus through the
# hosts' datacenter NICs — modeled as one 200 Gbps NIC shared by a
# 4-chip host, i.e. 6.25 GB/s per chip, for every generation.  That is
# an ASSUMPTION (no multislice measurement exists in this repo yet —
# PERF.md §23); the check_tables DCN anchor pins it so it cannot move
# silently, and the 32x ICI:DCN ratio on v5e is the whole reason the
# slice axis must carry the lightest collectives.
HARDWARE = {
    "v4": Hardware("v4", 275e12, 1.23e12, 32.0 * 1e9, 300e9, 6.25e9),
    "v5e": Hardware("v5e", 197e12, 0.81e12, 15.75 * 1e9, 200e9, 6.25e9),
    "v5p": Hardware("v5p", 459e12, 2.76e12, 95.0 * 1e9, 600e9, 6.25e9),
    "v6e": Hardware("v6e", 918e12, 1.64e12, 32.0 * 1e9, 448e9, 6.25e9),
}


def generation_from_topology(topology: str) -> str:
    """'v5e:2x2' -> 'v5e' (the topology-string prefix jax's
    ``get_topology_desc`` accepts)."""
    return topology.split(":", 1)[0].strip().lower()


def n_chips_from_topology(topology: str) -> int:
    """'v5e:2x2' -> 4, without initializing a compile-only backend."""
    _, _, dims = topology.partition(":")
    n = 1
    for d in dims.split("x"):
        n *= int(d)
    return n


def get_hardware(generation: str) -> Hardware:
    gen = generation.split(":", 1)[0].strip().lower()
    if gen not in HARDWARE:
        raise KeyError(f"unknown TPU generation {generation!r}; "
                       f"have {sorted(HARDWARE)}")
    return HARDWARE[gen]


def score(generation: str, *, flops: float, bytes_accessed: float,
          peak_memory_bytes: float | None = None,
          contains_scan: bool = False) -> dict:
    """Roofline score for one compiled program on one chip generation.

    Returns a JSON-able dict:
      t_mxu_ms / t_hbm_ms — compute and bandwidth rooflines
      predicted_ms        — max of the two (the binding one); a LOWER bound
      bound               — "mxu" | "hbm" (which roofline binds)
      fits                — peak_memory_bytes <= HBM capacity (None if the
                            caller didn't supply memory)
      bytes_lower_bound   — §8 scan caveat: totals undercount, so
                            predicted_ms is even more of a lower bound
    """
    hw = get_hardware(generation)
    t_mxu_ms = flops / hw.bf16_flops * 1e3
    t_hbm_ms = bytes_accessed / hw.hbm_bytes_per_s * 1e3
    fits = None
    if peak_memory_bytes is not None:
        fits = peak_memory_bytes <= hw.hbm_capacity_bytes
    return {
        "generation": hw.generation,
        "flops": flops,
        "bytes": bytes_accessed,
        "t_mxu_ms": round(t_mxu_ms, 2),
        "t_hbm_ms": round(t_hbm_ms, 2),
        "predicted_ms": round(max(t_mxu_ms, t_hbm_ms), 2),
        "bound": "hbm" if t_hbm_ms >= t_mxu_ms else "mxu",
        "fits": fits,
        "peak_memory_bytes": peak_memory_bytes,
        "bytes_lower_bound": bool(contains_scan),
    }


@dataclasses.dataclass(frozen=True)
class DecodeScore:
    """Roofline upper bound on serving decode throughput for one chip."""

    generation: str
    bytes_params: float        # weights read once per step
    bytes_kv: float            # KV cache read (+ the step's writes)
    bytes_per_step: float
    flops_per_step: float
    t_step_ms: float           # lower bound on one decode step
    bound: str                 # "hbm" | "mxu"
    tokens_per_s: float        # slots / t_step — one chip, upper bound
    tokens_per_s_per_chip: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def decode_score(*, param_bytes: float, kv_bytes_per_token: float,
                 slots: int, context: int, generation: str = "v5e",
                 param_dtype_bytes: int = 4) -> DecodeScore:
    """Analytic tokens/sec UPPER bound for the batched decode step.

    Decode at query length 1 is memory-bound on every current TPU: the
    step must stream every weight byte once (batch amortizes it across
    ``slots`` tokens but not below one full read) plus each slot's live
    KV window (``context`` cached tokens at ``kv_bytes_per_token`` =
    ``CacheSpec.bytes_per_token()``, all layers, K+V) and write this
    step's new KV entry.  FLOPs are the weight matmuls (2 * params per
    token); attention FLOPs at query length 1 are negligible beside
    them, keeping the bound honest (lower t, higher tokens/sec).

    One chip, replica-local (the ``serve-dp-decode`` audit proves plain
    DP serving adds no collective time) — so the per-chip number IS the
    chip number, and fleet throughput scales linearly until the
    scheduler runs out of requests.
    """
    if slots < 1 or context < 0:
        raise ValueError(f"need slots >= 1, context >= 0; "
                         f"got {slots}, {context}")
    hw = get_hardware(generation)
    bytes_kv = float(slots * (context + 1) * kv_bytes_per_token)
    bytes_per_step = float(param_bytes) + bytes_kv
    flops = 2.0 * (float(param_bytes) / param_dtype_bytes) * slots
    t_hbm_ms = bytes_per_step / hw.hbm_bytes_per_s * 1e3
    t_mxu_ms = flops / hw.bf16_flops * 1e3
    t_step_ms = max(t_hbm_ms, t_mxu_ms)
    tokens_per_s = slots / (t_step_ms / 1e3) if t_step_ms > 0 else 0.0
    return DecodeScore(
        generation=hw.generation,
        bytes_params=float(param_bytes),
        bytes_kv=bytes_kv,
        bytes_per_step=bytes_per_step,
        flops_per_step=flops,
        t_step_ms=round(t_step_ms, 4),
        bound="hbm" if t_hbm_ms >= t_mxu_ms else "mxu",
        tokens_per_s=round(tokens_per_s, 2),
        tokens_per_s_per_chip=round(tokens_per_s, 2),
    )


# Ring-algorithm wire multipliers on (n-1)/n * bytes: an all-reduce moves
# every byte twice (reduce-scatter phase + all-gather phase); the one-phase
# collectives move it once.  collective-permute is a single neighbor hop.
_COMM_RING_FACTORS = {
    "all-reduce": 2.0,
    "all-gather": 1.0,
    "reduce-scatter": 1.0,
    "all-to-all": 1.0,
    "collective-permute": 1.0,
}


def comm_ms(generation: str, kind: str, nbytes: float,
            n_devices: int) -> float:
    """Predicted ICI milliseconds for one collective: ring model,
    ``factor * (n-1)/n * bytes / ici_bw``.  ``nbytes`` must be the op's
    bytes as PARSED FROM THE COMPILED HLO (``hlo_audit``'s ruler — an s8
    payload counts 1 byte/element), never re-derived from the program's
    accumulation dtype: a quantized wire moves a quarter of the f32
    bytes and the prediction has to see that."""
    hw = get_hardware(generation)
    if hw.ici_bytes_per_s <= 0 or n_devices <= 1:
        return 0.0
    factor = _COMM_RING_FACTORS.get(kind, 1.0)
    scale = (n_devices - 1) / n_devices
    return factor * scale * float(nbytes) / hw.ici_bytes_per_s * 1e3


def dcn_ms(generation: str, kind: str, nbytes: float,
           n_slices: int) -> float:
    """Predicted DCN milliseconds for one cross-slice collective: the
    same ring model as :func:`comm_ms` but over the slice count and the
    per-chip DCN share — ``factor * (s-1)/s * bytes / dcn_bw``.  Like
    the ICI model, ``nbytes`` is the op's bytes as parsed from the
    compiled HLO (quantized wires count their actual payload)."""
    hw = get_hardware(generation)
    if hw.dcn_bytes_per_s <= 0 or n_slices <= 1:
        return 0.0
    factor = _COMM_RING_FACTORS.get(kind, 1.0)
    scale = (n_slices - 1) / n_slices
    return factor * scale * float(nbytes) / hw.dcn_bytes_per_s * 1e3


def hbm_ms(generation: str, nbytes: float) -> float:
    """Predicted HBM milliseconds to stream ``nbytes`` on one chip — the
    same bandwidth roofline as :func:`score`'s ``t_hbm_ms``, exposed per
    byte count so the schedule auditor can price interleavable compute
    (compute ops are overwhelmingly bandwidth-bound at audit scale, so
    the byte roofline is the honest lower bound on how long they give a
    scheduler to hide a collective behind)."""
    hw = get_hardware(generation)
    return float(nbytes) / hw.hbm_bytes_per_s * 1e3


def comm_score(generation: str, report, n_devices: int) -> dict:
    """Per-kind predicted comm rows for one program's collectives.

    ``report`` is an ``hlo_audit.CollectiveReport`` (or anything with
    ``bytes_by_kind()``).  Wire-dtype awareness comes from the report
    itself: its byte totals were counted off the optimized HLO's result
    shapes, so an int8-block program's a2a/all-gather rows carry ~1/4
    the bytes of the f32 all-reduce they replaced.  ``t_ici_ms`` totals
    are a LOWER bound (assumes zero overlap loss, full ring bandwidth).
    """
    by_kind = report.bytes_by_kind()
    rows = [
        {"kind": k, "bytes": int(b),
         "t_ici_ms": round(comm_ms(generation, k, b, n_devices), 4)}
        for k, b in sorted(by_kind.items())
    ]
    return {
        "generation": get_hardware(generation).generation,
        "n_devices": int(n_devices),
        "rows": rows,
        "comm_bytes": int(sum(r["bytes"] for r in rows)),
        "t_ici_ms": round(sum(r["t_ici_ms"] for r in rows), 4),
    }


def comm_split_score(generation: str, split: dict, *, n_devices: int,
                     n_slices: int) -> dict:
    """Per-kind predicted comm rows with the wire attributed to its
    fabric: ``split`` is shardflow's ICI/DCN byte attribution
    (``{"ici": {kind: bytes}, "dcn": {kind: bytes}}`` — a collective
    whose replica groups span slices is charged to DCN).  ICI rows are
    priced over the full device ring, DCN rows over the slice ring and
    the per-chip DCN share; on v5e the ~32x bandwidth gap between the
    two columns is the multi-slice placement signal."""
    rows = []
    for fabric, priced in (("ici", lambda k, b: comm_ms(
            generation, k, b, n_devices)),
                           ("dcn", lambda k, b: dcn_ms(
            generation, k, b, n_slices))):
        for kind, nbytes in sorted((split.get(fabric) or {}).items()):
            rows.append({"fabric": fabric, "kind": kind,
                         "bytes": int(nbytes),
                         "t_ms": round(priced(kind, nbytes), 4)})
    ici_ms = sum(r["t_ms"] for r in rows if r["fabric"] == "ici")
    dcn_ms_total = sum(r["t_ms"] for r in rows if r["fabric"] == "dcn")
    return {
        "generation": get_hardware(generation).generation,
        "n_devices": int(n_devices),
        "n_slices": int(n_slices),
        "rows": rows,
        "ici_bytes": int(sum(r["bytes"] for r in rows
                             if r["fabric"] == "ici")),
        "dcn_bytes": int(sum(r["bytes"] for r in rows
                             if r["fabric"] == "dcn")),
        "t_ici_ms": round(ici_ms, 4),
        "t_dcn_ms": round(dcn_ms_total, 4),
    }


def contains_scan(hlo_text: str) -> bool:
    """§8 detector: a lowered-to-TPU ``lax.scan`` shows up as an HLO while
    loop.  (Interpret-mode pallas also lowers as a while loop — one more
    reason the sweep forces real Mosaic lowering.)"""
    return "while(" in hlo_text or " while " in hlo_text


def score_compiled(compiled, generation: str) -> dict:
    """Score a jax AOT ``compiled`` object (``.lower(...).compile()``).

    Duck-typed so this module needs no jax import.  Any missing analysis
    (some backends return None) degrades to zeros rather than raising —
    the search driver records the row either way.
    """
    try:
        ca = compiled.cost_analysis() or {}
    except Exception:  # noqa: BLE001 — cost_analysis is best-effort too
        ca = {}
    if isinstance(ca, (list, tuple)):  # older jax: one dict per device
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0))
    nbytes = float(ca.get("bytes accessed", 0.0))
    peak = None
    try:
        ma = compiled.memory_analysis()
        peak = float(ma.temp_size_in_bytes + ma.argument_size_in_bytes)
    except Exception:  # noqa: BLE001 — memory_analysis is best-effort
        pass
    try:
        scan = contains_scan(compiled.as_text())
    except Exception:  # noqa: BLE001
        scan = False
    return score(generation, flops=flops, bytes_accessed=nbytes,
                 peak_memory_bytes=peak, contains_scan=scan)


def check_tables() -> list:
    """Sanity checks for the CI gate (analysis `_run_tune_check`): every
    generation has positive peaks, and the v5e row reproduces PERF.md §2's
    recorded ResNet-50 b=512 anchors (1.252e13 flops / 1.435e11 bytes ->
    63.6 ms MXU, 177 ms HBM, bandwidth-bound).  Returns a list of problem
    strings; empty means healthy."""
    problems = []
    for gen, hw in sorted(HARDWARE.items()):
        if not (hw.bf16_flops > 0 and hw.hbm_bytes_per_s > 0
                and hw.hbm_capacity_bytes > 0):
            problems.append(f"hardware table {gen}: non-positive peak")
        if hw.bf16_flops / hw.hbm_bytes_per_s > 1000:
            problems.append(f"hardware table {gen}: arithmetic intensity "
                            f"ridge >1000 flops/byte — units wrong?")
    s = score("v5e", flops=1.252e13, bytes_accessed=1.435e11)
    if abs(s["t_mxu_ms"] - 63.6) > 0.5:
        problems.append(f"v5e MXU anchor drifted: {s['t_mxu_ms']} != 63.6 ms")
    if abs(s["t_hbm_ms"] - 177.2) > 0.5:
        problems.append(f"v5e HBM anchor drifted: {s['t_hbm_ms']} != 177.2 ms")
    if s["bound"] != "hbm":
        problems.append("v5e ResNet-50 anchor must be bandwidth-bound")
    for gen, hw in sorted(HARDWARE.items()):
        if not hw.ici_bytes_per_s > 0:
            problems.append(f"hardware table {gen}: non-positive ICI peak")
    # Comm-model anchor: ResNet-50's 102.23 MB f32 grad all-reduce on a
    # v5e 2x2 ring is 2 * 3/4 * 1.0223e8 / 200e9 = 0.767 ms, and the same
    # gradient on the int8-block wire (bytes/4 by the HLO ruler) predicts
    # exactly a quarter of that — the wire-dtype awareness is the invariant.
    t_f32 = comm_ms("v5e", "all-reduce", 1.0223e8, 4)
    t_s8 = comm_ms("v5e", "all-reduce", 1.0223e8 / 4, 4)
    if abs(t_f32 - 0.767) > 0.005:
        problems.append(f"v5e comm anchor drifted: {t_f32:.4f} != 0.767 ms")
    if abs(t_s8 * 4 - t_f32) > 1e-9:
        problems.append("comm model is not linear in wire bytes — "
                        "int8 prediction must be f32/4")
    # DCN anchor (mirrors the ICI one): the same 102.23 MB grad
    # all-reduce crossing 2 slices is 2 * 1/2 * 1.0223e8 / 6.25e9 =
    # 16.357 ms — ~21x the 4-chip ICI ring, which is the whole point of
    # attributing the split.  Linearity in bytes is pinned too, so the
    # dcn_bytes_per_s table cannot silently regress shape.
    for gen, hw in sorted(HARDWARE.items()):
        if not hw.dcn_bytes_per_s > 0:
            problems.append(f"hardware table {gen}: non-positive DCN peak")
        elif hw.dcn_bytes_per_s >= hw.ici_bytes_per_s:
            problems.append(f"hardware table {gen}: DCN share >= ICI peak "
                            f"— the fabrics are swapped")
    t_dcn = dcn_ms("v5e", "all-reduce", 1.0223e8, 2)
    if abs(t_dcn - 16.357) > 0.01:
        problems.append(f"v5e DCN anchor drifted: {t_dcn:.4f} != 16.357 ms")
    if abs(dcn_ms("v5e", "all-reduce", 2 * 1.0223e8, 2) - 2 * t_dcn) > 1e-9:
        problems.append("DCN model is not linear in wire bytes")
    if dcn_ms("v5e", "all-reduce", 1.0223e8, 1) != 0.0:
        problems.append("DCN model must price a single-slice program at "
                        "exactly zero — there is no cross-slice wire")
    # hbm_ms must be the same ruler as score()'s t_hbm_ms — the overlap
    # scorer prices interleavable compute with it, and a divergence would
    # let the two rooflines disagree about the identical byte count.
    t_hbm = hbm_ms("v5e", 1.435e11)
    if abs(t_hbm - score("v5e", flops=0.0,
                         bytes_accessed=1.435e11)["t_hbm_ms"]) > 0.05:
        problems.append(f"hbm_ms diverged from score()'s t_hbm_ms ruler: "
                        f"{t_hbm:.2f} ms on the §2 anchor bytes")
    return problems
