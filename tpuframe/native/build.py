"""Lazy g++ build of the native library, cached by source hash.

No pybind11 in this environment (see repo docs) — the ABI is plain C,
consumed via ctypes.  Rebuilds only when ``src/tpuframe_native.cc`` changes;
concurrent builders (multi-process test runs) race benignly on a temp file +
atomic rename.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile

_SRC = os.path.join(os.path.dirname(__file__), "src", "tpuframe_native.cc")
_FFI_SRC = os.path.join(os.path.dirname(__file__), "src", "tpuframe_ffi.cc")
_OUT_DIR = os.path.join(os.path.dirname(__file__), "_build")


def _compile(src: str, stem: str, extra_flags: list[str], *,
             salt: str = "", force: bool = False) -> str:
    """``salt`` joins the cache key for inputs outside the source file
    (e.g. the jaxlib whose headers an FFI build compiles against)."""
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    h.update(("\0" + salt + "\0" + " ".join(extra_flags)).encode())
    digest = h.hexdigest()[:16]
    out = os.path.join(_OUT_DIR, f"{stem}_{digest}.so")
    if os.path.exists(out) and not force:
        return out
    os.makedirs(_OUT_DIR, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_OUT_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
             *extra_flags, src, "-o", tmp],
            check=True, capture_output=True, text=True)
        os.replace(tmp, out)  # atomic: concurrent builders converge
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return out


def build(force: bool = False) -> str:
    """Compile (if needed) and return the host-runtime library path."""
    return _compile(_SRC, "libtpuframe_native", [], force=force)


def build_ffi() -> str:
    """Compile (if needed) and return the XLA-FFI kernel library path.

    Needs the XLA FFI headers jaxlib ships (header-only C++ API) — unlike
    the dependency-free host runtime, so it is a separate .so with its own
    build, keyed by the jaxlib version too (a jaxlib upgrade changes the
    FFI headers the kernel compiles against — a stale .so must not be
    served to a new runtime); consumers degrade gracefully when the
    headers or toolchain are missing."""
    import jax.ffi
    import jaxlib

    return _compile(_FFI_SRC, "libtpuframe_ffi",
                    [f"-I{jax.ffi.include_dir()}"],
                    salt=f"jaxlib-{jaxlib.__version__}")
