"""Lazy g++ build of the native library, cached by source hash.

No pybind11 in this environment (see repo docs) — the ABI is plain C,
consumed via ctypes.  Rebuilds only when ``src/tpuframe_native.cc`` changes;
concurrent builders (multi-process test runs) race benignly on a temp file +
atomic rename.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile

_SRC = os.path.join(os.path.dirname(__file__), "src", "tpuframe_native.cc")
_OUT_DIR = os.path.join(os.path.dirname(__file__), "_build")


def build(force: bool = False) -> str:
    """Compile (if needed) and return the shared-library path."""
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_OUT_DIR, f"libtpuframe_native_{digest}.so")
    if os.path.exists(out) and not force:
        return out
    os.makedirs(_OUT_DIR, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_OUT_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
             _SRC, "-o", tmp],
            check=True, capture_output=True, text=True)
        os.replace(tmp, out)  # atomic: concurrent builders converge
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return out
