// XLA FFI custom-call kernel — the SURVEY.md §3b "native custom-call
// demonstrator": C++ running INSIDE a compiled XLA program (vs the ctypes
// host runtime in tpuframe_native.cc, which runs outside the graph).
//
//   tf_normalize_u8: y = (x/255 - mean[c]) / std[c] over [..., C] uint8 —
//   the canonical DataLoader-worker transform (torchvision
//   ToTensor+Normalize), multithreaded over rows.  CPU backend only: on
//   TPU this op belongs to XLA fusion on-device (and custom C++ cannot run
//   there — that's what pallas kernels are for); on the CPU hosts of the
//   fake cluster it demonstrates the in-graph native path the reference
//   gets from Horovod's C++/cuDNN stack.
//
// Built by tpuframe/native/build.py::build_ffi with -I jax.ffi.include_dir()
// (header-only XLA FFI C++ API; no libraries linked).

#include <algorithm>
#include <cstdint>
#include <thread>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error NormalizeU8Impl(ffi::Buffer<ffi::U8> x,
                                  ffi::Buffer<ffi::F32> mean,
                                  ffi::Buffer<ffi::F32> stddev,
                                  ffi::ResultBuffer<ffi::F32> y) {
  const auto dims = x.dimensions();
  if (dims.size() < 1) {
    return ffi::Error::InvalidArgument("tf_normalize_u8: rank >= 1 required");
  }
  const int64_t c = dims.back();
  int64_t rows = 1;
  for (size_t i = 0; i + 1 < dims.size(); ++i) rows *= dims[i];
  if (static_cast<int64_t>(mean.element_count()) != c ||
      static_cast<int64_t>(stddev.element_count()) != c) {
    return ffi::Error::InvalidArgument(
        "tf_normalize_u8: mean/std length must equal the channel dim");
  }
  const uint8_t* src = x.typed_data();
  const float* mu = mean.typed_data();
  const float* sd = stddev.typed_data();
  float* dst = y->typed_data();

  // Precompute per-channel scale/shift: y = x * (1/(255*sd)) - mu/sd.
  std::vector<float> scale(c), shift(c);
  for (int64_t j = 0; j < c; ++j) {
    scale[j] = 1.0f / (255.0f * sd[j]);
    shift[j] = -mu[j] / sd[j];
  }

  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const uint8_t* in = src + r * c;
      float* out = dst + r * c;
      for (int64_t j = 0; j < c; ++j) {
        out[j] = static_cast<float>(in[j]) * scale[j] + shift[j];
      }
    }
  };

  const int64_t total = rows * c;
  int64_t n_threads =
      std::min<int64_t>(std::max(1u, std::thread::hardware_concurrency() / 2),
                        rows);
  if (n_threads <= 1 || total < (1 << 20)) {
    work(0, rows);
    return ffi::Error::Success();
  }
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  const int64_t chunk = (rows + n_threads - 1) / n_threads;
  for (int64_t t = 0; t < n_threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(lo + chunk, rows);
    if (lo >= hi) break;
    workers.emplace_back(work, lo, hi);
  }
  for (auto& w : workers) w.join();
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(TfNormalizeU8, NormalizeU8Impl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::U8>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());
