// tpuframe native runtime — host-side C++ for the paths the reference keeps
// native (SURVEY.md §3b: Horovod's runtime is C++; on TPU the *device* side
// belongs to XLA, the host side — batch assembly and checkpoint integrity —
// is implemented here).
//
//   * tf_gather_rows: multi-threaded gather of dataset rows into a batch
//     buffer. This is the per-step host work of the input pipeline (numpy
//     fancy indexing is single-threaded and GIL-bound; this runs on a small
//     thread pool with the GIL released by the ctypes call).
//   * tf_crc32c: Castagnoli CRC (slicing-by-8) for checkpoint integrity
//     (the same polynomial GCS uses for object checksums).
//
// Built by tpuframe/native/build.py: g++ -O3 -shared -fPIC, no external
// dependencies.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Gather n_idx rows of row_bytes each: dst[i] = src[indices[i]].
// Rows are raw bytes — dtype-agnostic; caller guarantees bounds.
void tf_gather_rows(const char* src, const int64_t* indices, int64_t n_idx,
                    int64_t row_bytes, char* dst, int32_t n_threads) {
  if (n_idx <= 0) return;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_idx) n_threads = static_cast<int32_t>(n_idx);
  // Small batches: threading overhead dominates, copy inline.
  if (n_threads == 1 || n_idx * row_bytes < (1 << 20)) {
    for (int64_t i = 0; i < n_idx; ++i) {
      std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                  row_bytes);
    }
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  const int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = std::min(lo + chunk, n_idx);
    if (lo >= hi) break;
    workers.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i) {
        std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                    row_bytes);
      }
    });
  }
  for (auto& w : workers) w.join();
}

// ---------------------------------------------------------------------------
// crc32c (Castagnoli, poly 0x1EDC6F41 reflected = 0x82F63B78), slicing-by-8.
// ---------------------------------------------------------------------------

namespace {
uint32_t kTable[8][256];
std::atomic<bool> kTableInit{false};

void init_table() {
  bool expected = false;
  static std::atomic<bool> building{false};
  if (kTableInit.load(std::memory_order_acquire)) return;
  if (building.exchange(true)) {
    while (!kTableInit.load(std::memory_order_acquire)) {}
    return;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j)
      crc = (crc >> 1) ^ (0x82F63B78u & (~(crc & 1) + 1));
    kTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = kTable[0][i];
    for (int k = 1; k < 8; ++k) {
      crc = kTable[0][crc & 0xFF] ^ (crc >> 8);
      kTable[k][i] = crc;
    }
  }
  kTableInit.store(true, std::memory_order_release);
  (void)expected;
}
}  // namespace

uint32_t tf_crc32c(const uint8_t* data, int64_t n, uint32_t seed) {
  init_table();
  uint32_t crc = ~seed;
  while (n >= 8) {
    uint64_t word;
    std::memcpy(&word, data, 8);
    crc ^= static_cast<uint32_t>(word);
    uint32_t hi = static_cast<uint32_t>(word >> 32);
    crc = kTable[7][crc & 0xFF] ^ kTable[6][(crc >> 8) & 0xFF] ^
          kTable[5][(crc >> 16) & 0xFF] ^ kTable[4][crc >> 24] ^
          kTable[3][hi & 0xFF] ^ kTable[2][(hi >> 8) & 0xFF] ^
          kTable[1][(hi >> 16) & 0xFF] ^ kTable[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n-- > 0) crc = kTable[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

}  // extern "C"
