"""Native (C++) host runtime — reference parity for Horovod's native layer.

SURVEY.md §3b: the reference's heavy machinery is C++ (coordinator, fusion
buffer, NCCL/MPI glue).  On TPU the device side of that is XLA's job; the
host-side pieces that still benefit from native code live here:

  * :func:`gather_rows` — multi-threaded, GIL-released batch assembly for
    the input pipeline (ShardedLoader's per-step host work).
  * :func:`crc32c` — checkpoint integrity checksums (same polynomial GCS
    uses for object checksums).

The library builds lazily from ``src/tpuframe_native.cc`` with g++ (see
``build.py``) and every consumer degrades gracefully to a pure-Python path
when the toolchain or binary is unavailable — capability, not a hard dep.
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_LOAD_FAILED = False


def _load() -> ctypes.CDLL | None:
    global _LIB, _LOAD_FAILED
    if _LIB is not None or _LOAD_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LOAD_FAILED:
            return _LIB
        try:
            from tpuframe.native.build import build

            path = build()
            lib = ctypes.CDLL(path)
            lib.tf_gather_rows.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p,
                ctypes.c_int32]
            lib.tf_gather_rows.restype = None
            lib.tf_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                      ctypes.c_uint32]
            lib.tf_crc32c.restype = ctypes.c_uint32
            _LIB = lib
        except Exception:  # noqa: BLE001 — any failure → Python fallback
            _LOAD_FAILED = True
    return _LIB


def available() -> bool:
    return _load() is not None


def gather_rows(src: np.ndarray, indices: np.ndarray,
                out: np.ndarray | None = None,
                n_threads: int | None = None) -> np.ndarray:
    """``out[i] = src[indices[i]]`` for row-major ``src``; multi-threaded
    native copy with the GIL released, numpy fancy-indexing fallback."""
    lib = _load()
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(indices, np.int64)
    if idx.ndim != 1:
        raise ValueError("indices must be 1-D")
    if np.any(idx < 0) or (len(idx) and int(idx.max()) >= len(src)):
        raise IndexError("gather index out of range")
    if out is None:
        out = np.empty((len(idx), *src.shape[1:]), src.dtype)
    if lib is None:
        np.take(src, idx, axis=0, out=out)
        return out
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    if n_threads is None:
        n_threads = min(8, os.cpu_count() or 1)
    lib.tf_gather_rows(
        src.ctypes.data_as(ctypes.c_char_p),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(idx), row_bytes, out.ctypes.data_as(ctypes.c_char_p),
        n_threads)
    return out


def crc32c(data: bytes | np.ndarray, seed: int = 0) -> int:
    """Castagnoli CRC-32 (native slicing-by-8, zlib-based fallback is NOT
    compatible — pure-Python fallback implements the same polynomial)."""
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data).tobytes()
    lib = _load()
    if lib is not None:
        return int(lib.tf_crc32c(data, len(data), seed))
    return _crc32c_py(data, seed)


_PY_TABLE: list[int] | None = None


def _crc32c_py(data: bytes, seed: int) -> int:
    global _PY_TABLE
    if _PY_TABLE is None:
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
            table.append(crc)
        _PY_TABLE = table
    crc = ~seed & 0xFFFFFFFF
    for b in data:
        crc = _PY_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return (~crc) & 0xFFFFFFFF
