"""TensorBoard event-file sink — scalars in stock-TensorBoard format.

Reference parity (SURVEY.md §5.5): "TensorBoard event files written to GCS".
No tensorflow/tensorboard import needed to *write*: a scalar event is a tiny
``Event``/``Summary`` protobuf (the wire format is frozen) framed as a
TFRecord whose checksum is CRC32C — the same kernel checkpoint integrity uses
(``tpuframe.native``).  Everything is hand-encoded here, ~60 lines, so the
sink works on a bare TPU-VM image.

Files land as ``<log_dir>/events.out.tfevents.<ts>.<host>.<pid>`` — exactly
the glob stock TensorBoard scans — on local disk or GCS (``gs://`` paths go
through ``tpuframe.data.gcs``).  Local files append only the new records on
each flush (O(new data), the buffer is drained); GCS objects are immutable,
so only ``gs://`` paths rewrite the accumulated stream per flush — cheap
for scalar-only files.

Verified readable by tensorboard's own ``EventFileLoader`` in
``tests/test_observability.py``.
"""

from __future__ import annotations

import os
import socket
import struct
import time

from tpuframe.data import gcs


# --- minimal protobuf wire encoding (only what Event/Summary need) ---------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, data: bytes) -> bytes:
    return _key(field, 2) + _varint(len(data)) + data


def _double_field(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float_field(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _varint_field(field: int, n: int) -> bytes:
    return _key(field, 0) + _varint(n)


def _scalar_event(step: int, scalars: dict[str, float],
                  wall_time: float) -> bytes:
    """Event{wall_time=1, step=2, summary=5{value=1{tag=1, simple_value=2}*}}"""
    summary = b"".join(
        _len_field(1, _len_field(1, tag.encode()) + _float_field(2, float(v)))
        for tag, v in scalars.items())
    return (_double_field(1, wall_time) + _varint_field(2, step)
            + _len_field(5, summary))


def _file_version_event() -> bytes:
    """Event{wall_time=1, file_version=3} — TB requires this first record."""
    return _double_field(1, time.time()) + _len_field(3, b"brain.Event:2")


# --- TFRecord framing ------------------------------------------------------

def _masked_crc(data: bytes) -> int:
    from tpuframe import native

    crc = native.crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def _tfrecord(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", _masked_crc(header))
            + data + struct.pack("<I", _masked_crc(data)))


# --- the writer ------------------------------------------------------------

class SummaryWriter:
    """Append-only scalar event writer for one run directory.

    ``add_scalars(step, {"loss": 0.3, "acc": 0.9}, prefix="train")`` writes
    tags ``train/loss``, ``train/acc``.  Buffers in memory; ``flush()``
    persists — incremental append on local disk, whole-object rewrite only
    on GCS (immutable objects).
    """

    def __init__(self, log_dir: str, *, flush_every: int = 20):
        self.log_dir = log_dir
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}.{os.getpid()}")
        self.path = gcs.join(log_dir, fname)
        self._gcs = gcs.is_gcs_path(self.path)
        self._buf = bytearray(_tfrecord(_file_version_event()))
        self._pending = 0
        self._flush_every = flush_every
        gcs.makedirs(log_dir)

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self.add_scalars(step, {tag: value})

    def add_scalars(self, step: int, scalars: dict, *,
                    prefix: str = "") -> None:
        clean = {(f"{prefix}/{k}" if prefix else k): float(v)
                 for k, v in scalars.items()
                 if isinstance(v, (int, float)) or hasattr(v, "item")}
        if not clean:
            return
        self._buf += _tfrecord(_scalar_event(step, clean, time.time()))
        self._pending += 1
        if self._pending >= self._flush_every:
            self.flush()

    def flush(self) -> None:
        if not (self._pending or not gcs.exists(self.path)):
            return
        if self._gcs:
            # GCS objects are immutable: rewrite the whole record stream
            # (scalar event files stay small).
            gcs.write_bytes(self.path, bytes(self._buf))
        else:
            # Local disk: append only what's new — O(new data); flushed
            # history lives on disk, not in memory.
            with open(self.path, "ab") as f:
                f.write(bytes(self._buf))
            del self._buf[:]
        self._pending = 0

    def close(self) -> None:
        self.flush()
