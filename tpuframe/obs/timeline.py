"""Profiling hooks — the HOROVOD_TIMELINE replacement (SURVEY.md §5.1).

Horovod records per-tensor negotiate/fuse/NCCL phases to a Chrome trace; on
TPU the equivalent visibility comes from the XLA/jax profiler: a perfetto/
TensorBoard trace of the compiled step, including the all-reduce ops and
their overlap with compute.  ``TPUFRAME_TRACE_DIR`` env or config triggers a
trace of steps [start, start+count) in the harness.
"""

from __future__ import annotations

import contextlib
import os
import time

import jax


ENV_TRACE_STEPS = "TPUFRAME_TRACE_STEPS"
ENV_PROFILER_PORT = "TPUFRAME_PROFILER_PORT"


def parse_trace_steps(spec: str | None) -> tuple[int, int] | None:
    """Parse ``TPUFRAME_TRACE_STEPS="<start>:<count>"`` into
    ``(start, count)``.  Returns None for unset, malformed, or degenerate
    (count < 1, start < 0) specs — a bad knob must not kill the run."""
    if not spec or not spec.strip():
        return None
    parts = spec.strip().split(":")
    if len(parts) != 2:
        return None
    try:
        start, count = int(parts[0]), int(parts[1])
    except ValueError:
        return None
    if start < 0 or count < 1:
        return None
    return start, count


def start_profiler_server(port: int = 9012) -> bool:
    """On-demand profiling endpoint (TensorBoard 'capture profile')."""
    try:
        jax.profiler.start_server(port)
        return True
    except Exception:
        return False


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Trace a window of steps to ``log_dir`` (viewable in
    TensorBoard/perfetto; the analog of one Horovod timeline segment)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a traced window (maps to a trace event)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimeline:
    """Host-side chrome-trace timeline — the direct HOROVOD_TIMELINE analog.

    Horovod's timeline shows per-tensor collective phases; under one-program
    SPMD the interesting host phases are coarser: data wait (input pipeline),
    step submit/execute, eval, checkpoint.  Events accumulate in memory and
    flush as a Chrome ``chrome://tracing`` / Perfetto JSON array on close.

    Enable via ``TPUFRAME_TIMELINE=/path/trace.json`` (env parity with
    ``HOROVOD_TIMELINE=file.json``) — the harness wires the phases.
    """

    def __init__(self, path: str):
        # On a multi-host slice with a shared filesystem, every process
        # writing the same path would clobber each other's full-file dump;
        # suffix with the process index so each host's timeline survives.
        if jax.process_count() > 1:
            root, ext = os.path.splitext(path)
            path = f"{root}.proc{jax.process_index()}{ext or '.json'}"
        self.path = path
        self._events: list[dict] = []
        self._t0 = time.perf_counter()

    @classmethod
    def from_env(cls) -> "StepTimeline | None":
        path = os.environ.get("TPUFRAME_TIMELINE")
        return cls(path) if path else None

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextlib.contextmanager
    def phase(self, name: str, **args):
        start = self._now_us()
        try:
            yield
        finally:
            self._events.append({
                "name": name, "ph": "X", "ts": start,
                "dur": self._now_us() - start,
                "pid": jax.process_index(), "tid": 0,
                **({"args": args} if args else {}),
            })

    def instant(self, name: str, **args) -> None:
        self._events.append({
            "name": name, "ph": "i", "ts": self._now_us(), "s": "p",
            "pid": jax.process_index(), "tid": 0,
            **({"args": args} if args else {}),
        })

    def close(self) -> None:
        import json

        with open(self.path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms"}, f)
