"""Profiling hooks — the HOROVOD_TIMELINE replacement (SURVEY.md §5.1).

Horovod records per-tensor negotiate/fuse/NCCL phases to a Chrome trace; on
TPU the equivalent visibility comes from the XLA/jax profiler: a perfetto/
TensorBoard trace of the compiled step, including the all-reduce ops and
their overlap with compute.  ``TPUFRAME_TRACE_DIR`` env or config triggers a
trace of steps [start, start+count) in the harness.
"""

from __future__ import annotations

import contextlib

import jax


def start_profiler_server(port: int = 9012) -> bool:
    """On-demand profiling endpoint (TensorBoard 'capture profile')."""
    try:
        jax.profiler.start_server(port)
        return True
    except Exception:
        return False


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Trace a window of steps to ``log_dir`` (viewable in
    TensorBoard/perfetto; the analog of one Horovod timeline segment)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a traced window (maps to a trace event)."""
    return jax.profiler.TraceAnnotation(name)
