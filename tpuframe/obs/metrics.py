"""Metrics & throughput logging.

Reference parity (SURVEY.md §5.5): rank-0-gated prints + allreduce-averaged
scalars; the north-star metric is images/sec/chip [B:2], so the rate meter is
first-class.  Output is stdout lines + a JSONL file (local or gs://-style via
append-on-host then upload at close).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from pathlib import Path

import jax

# ---------------------------------------------------------------------------
# Process-wide event counters.  The resilience layer bumps these from retry
# loops (``retry.gcs_read.retries`` etc.), which may run in checkpoint/data
# threads — hence the lock.  Deliberately not jax-aware: counters are
# per-host facts and must work before any backend exists.
# ---------------------------------------------------------------------------

_counters: dict[str, int] = {}
_counters_lock = threading.Lock()


def bump(name: str, n: int = 1) -> None:
    """Increment the process-wide counter ``name`` by ``n``.

    Callers are retry loops and cache listeners mid-recovery: this must
    be safe at any point in the process lifecycle — before any logger
    exists, after ``MetricLogger.close()``, during interpreter teardown —
    and never raise back into the instrumented seam."""
    try:
        with _counters_lock:
            _counters[name] = _counters.get(name, 0) + int(n)
    except Exception:  # noqa: BLE001 — teardown / bad n; drop the bump
        pass


def counters(prefix: str | None = None) -> dict[str, int]:
    """Snapshot of counters, optionally filtered to ``prefix``."""
    with _counters_lock:
        return {k: v for k, v in _counters.items()
                if prefix is None or k.startswith(prefix)}


def reset_counters(prefix: str | None = None) -> None:
    with _counters_lock:
        if prefix is None:
            _counters.clear()
        else:
            for k in [k for k in _counters if k.startswith(prefix)]:
                del _counters[k]


def counters_reset(prefix: str | None = None) -> None:
    """Test-friendly alias for :func:`reset_counters` (the obs v2 API
    name); both clear the process-wide counter table."""
    reset_counters(prefix)


class RateMeter:
    """Examples/sec with warmup exclusion (first N steps are compile+cache)
    and pause support so eval/checkpoint wall-clock doesn't deflate the
    training-throughput number (the north-star metric, [B:2])."""

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self._count = 0
        self._examples = 0
        self._t0: float | None = None
        self._excluded = 0.0

    def update(self, batch_examples: int) -> None:
        self._count += 1
        if self._count == self.warmup_steps:
            self._t0 = time.perf_counter()
            self._examples = 0
            self._excluded = 0.0
        elif self._count > self.warmup_steps:
            self._examples += batch_examples

    @contextlib.contextmanager
    def paused(self):
        """Exclude the wrapped wall-clock (eval passes, blocking saves)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._excluded += time.perf_counter() - t0

    def rate(self) -> float | None:
        """examples/sec since warmup, None until measurable."""
        if self._t0 is None or self._examples == 0:
            return None
        dt = time.perf_counter() - self._t0 - self._excluded
        return self._examples / dt if dt > 0 else None

    def per_chip(self) -> float | None:
        r = self.rate()
        return r / jax.device_count() if r is not None else None


class MetricLogger:
    """Rank-0-gated structured logging: stdout + JSONL (local file appended
    live; ``gs://`` paths uploaded as periodic segment objects so a crash
    loses at most one flush window and resumes never overwrite history)."""

    def __init__(self, log_file: str | None = None, *, stdout: bool = True,
                 gcs_flush_every: int = 50, tb_dir: str | None = None):
        from tpuframe.data import gcs

        self.primary = jax.process_index() == 0
        self.stdout = stdout
        self._fh = None
        self._gcs_path: str | None = None
        self._gcs_buf: list[str] = []
        self._gcs_segment = 0
        self._gcs_flush_every = gcs_flush_every
        self._tb = None
        if self.primary and tb_dir:
            # TensorBoard event-file sink (SURVEY.md §5.5) — local or gs://.
            from tpuframe.obs.tensorboard import SummaryWriter

            self._tb = SummaryWriter(tb_dir)
        if self.primary and log_file:
            if gcs.is_gcs_path(log_file):
                self._gcs_path = log_file
                # Unique run suffix: resumed runs append new segments instead
                # of overwriting the previous run's log at the same path.
                self._gcs_run = int(time.time())
            else:
                Path(log_file).parent.mkdir(parents=True, exist_ok=True)
                self._fh = open(log_file, "a", buffering=1)

    def log(self, step: int, metrics: dict, *, prefix: str = "train") -> None:
        if not self.primary:
            return
        clean = {k: (float(v) if hasattr(v, "item") or isinstance(v, (int, float))
                     else v) for k, v in metrics.items()}
        record = {"step": step, "prefix": prefix, "time": time.time(), **clean}
        line = json.dumps(record)
        if self._tb is not None:
            self._tb.add_scalars(step, clean, prefix=prefix)
        if self._fh:
            self._fh.write(line + "\n")
        elif self._gcs_path is not None:
            self._gcs_buf.append(line)
            if len(self._gcs_buf) >= self._gcs_flush_every:
                self._flush_gcs()
        if self.stdout:
            body = " ".join(f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}"
                            for k, v in clean.items())
            print(f"[{prefix} {step}] {body}", flush=True)

    def _flush_gcs(self) -> None:
        """Write the buffered lines as a new segment object
        (``<path>.<runid>.<seg>``) so crashes lose at most one window and
        resumed runs never clobber earlier segments; readers concatenate."""
        if not self._gcs_buf:
            return
        from tpuframe.data import gcs

        seg_path = f"{self._gcs_path}.{self._gcs_run}.{self._gcs_segment:04d}"
        gcs.write_bytes(seg_path, ("\n".join(self._gcs_buf) + "\n").encode())
        self._gcs_segment += 1
        self._gcs_buf = []

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
            self._tb = None
        if self._fh:
            self._fh.close()
            self._fh = None
        if self._gcs_path is not None:
            self._flush_gcs()
