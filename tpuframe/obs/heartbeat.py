"""Heartbeat / stall detection — HOROVOD_STALL_CHECK's TPU-native analog
(SURVEY.md §5.3).

Horovod's stall check warns when a rank hasn't submitted a tensor others are
waiting on.  Under compiled SPMD that class of bug can't occur (one program,
one collective order), but a *host* can stall: input pipeline starvation, a
hung GCS read, a dead coordinator.  This watchdog runs in a thread, watches a
step counter the training loop bumps, and logs (or calls back) when no step
completes within the window — the per-host symptom of any pod-wide stall.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

from tpuframe.obs import events

logger = logging.getLogger(__name__)


class Heartbeat:
    def __init__(self, *, timeout_s: float = 120.0, poll_s: float = 5.0,
                 on_stall: Callable[[float], None] | None = None,
                 arm_after_first_beat: bool = False):
        self.timeout_s = timeout_s
        self.poll_s = poll_s
        self.on_stall = on_stall
        # When True, the watchdog only arms once a first step has completed —
        # first-step latency includes XLA compilation, which is legitimate
        # and unbounded (the harness uses this mode).
        self.arm_after_first_beat = arm_after_first_beat
        self._beats = 0
        self._last_beat = time.monotonic()
        self._step = 0
        self._stalled = False
        self.stall_count = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat(self, step: int) -> None:
        """Call once per completed training step.  A beat after a stall
        re-arms the watchdog: a recovered run that stalls again reports a
        *second* stall instead of staying latched on the first one."""
        self._step = step
        self._beats += 1
        self._last_beat = time.monotonic()
        self._stalled = False

    @property
    def stalled(self) -> bool:
        return self._stalled

    def start(self) -> "Heartbeat":
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="tpuframe-heartbeat")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2 * self.poll_s)

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            if self.arm_after_first_beat and self._beats == 0:
                continue
            idle = time.monotonic() - self._last_beat
            if idle > self.timeout_s and not self._stalled:
                self._stalled = True
                self.stall_count += 1
                logger.warning(
                    "no training step completed in %.0fs (last step %d) — "
                    "input pipeline stall, hung I/O, or peer failure",
                    idle, self._step)
                events.emit("stall", last_step=self._step,
                            idle_s=round(idle, 3),
                            stall_count=self.stall_count)
                if self.on_stall:
                    try:
                        self.on_stall(idle)
                    except Exception:  # noqa: BLE001 — a broken callback must
                        # not kill the watchdog thread silently; keep watching.
                        logger.exception("on_stall callback raised")
