"""Observability (SURVEY.md §5.1/§5.5): metrics, throughput, profiling,
heartbeat/stall detection, structured run-event tracing, goodput/MFU
accounting and HBM telemetry — the TPU-native stand-ins for Horovod
Timeline and HOROVOD_STALL_CHECK, plus the ``python -m tpuframe.obs``
offline analyzer over ``events.<host>.jsonl`` logs."""

from tpuframe.obs import devmem, events, goodput  # noqa: F401
from tpuframe.obs.devmem import DevmemSampler  # noqa: F401
from tpuframe.obs.events import EventLog  # noqa: F401
from tpuframe.obs.goodput import GoodputMeter  # noqa: F401
from tpuframe.obs.metrics import MetricLogger, RateMeter  # noqa: F401
from tpuframe.obs.heartbeat import Heartbeat  # noqa: F401
from tpuframe.obs.timeline import (StepTimeline, profile_trace,  # noqa: F401
                                   start_profiler_server)
