"""Observability (SURVEY.md §5.1/§5.5): metrics, throughput, profiling,
heartbeat/stall detection — the TPU-native stand-ins for Horovod Timeline and
HOROVOD_STALL_CHECK."""

from tpuframe.obs.metrics import MetricLogger, RateMeter  # noqa: F401
from tpuframe.obs.heartbeat import Heartbeat  # noqa: F401
from tpuframe.obs.timeline import (StepTimeline, profile_trace,  # noqa: F401
                                   start_profiler_server)
