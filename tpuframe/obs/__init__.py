"""Observability (SURVEY.md §5.1/§5.5): metrics, throughput, profiling,
heartbeat/stall detection, structured run-event tracing, goodput/MFU
accounting and HBM telemetry — the TPU-native stand-ins for Horovod
Timeline and HOROVOD_STALL_CHECK, plus the ``python -m tpuframe.obs``
offline analyzer over ``events.<host>.jsonl`` logs.  The live half is
``exporter`` (OpenMetrics ``/metrics`` + ``/healthz``) and ``flight``
(the crash flight recorder)."""

from tpuframe.obs import devmem, events, exporter, flight  # noqa: F401
from tpuframe.obs import goodput  # noqa: F401
from tpuframe.obs.devmem import DevmemSampler  # noqa: F401
from tpuframe.obs.events import EventLog  # noqa: F401
from tpuframe.obs.exporter import MetricsExporter  # noqa: F401
from tpuframe.obs.flight import FlightRecorder  # noqa: F401
from tpuframe.obs.goodput import GoodputMeter  # noqa: F401
from tpuframe.obs.metrics import MetricLogger, RateMeter  # noqa: F401
from tpuframe.obs.heartbeat import Heartbeat  # noqa: F401
from tpuframe.obs.timeline import (StepTimeline, parse_trace_steps,  # noqa: F401
                                   profile_trace, start_profiler_server)
