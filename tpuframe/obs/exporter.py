"""Live telemetry plane — an OpenMetrics endpoint over the obs counters.

Everything else in ``tpuframe.obs`` is post-hoc: goodput, anomalies and
serve percentiles exist only after ``python -m tpuframe.obs`` runs over
the JSONL logs.  This module is the *live* half (the operational surface
Horovod shipped as its timeline/monitoring hooks, arXiv:1802.05799): a
stdlib ``http.server`` endpoint any Prometheus-style scraper can poll
while the run is still going.

Endpoints:

  ``/metrics``  OpenMetrics text exposition — ``obs.metrics`` counters
                (one ``tpuframe_events_total`` family, counter name as a
                label), plus whatever gauges/collectors the harness
                registered: live goodput bucket seconds, step index and
                step-time, devmem HBM peaks, serve TTFT/TPOT percentiles.
  ``/healthz``  200 while the registered health probe says healthy, 503
                otherwise — train.py wires the heartbeat watchdog here,
                so a stalled run flips unhealthy *before* the stall-abort
                kills it.

Enable via ``TPUFRAME_METRICS_PORT=<port>`` (0 = ephemeral; the bound
port lands on ``MetricsExporter.port`` for tests).  Scrape-less hosts
set ``TPUFRAME_METRICS_TEXTFILE=<path>`` instead (or additionally): every
``flush()`` atomically rewrites the same exposition text for a
node-exporter-style textfile collector to pick up.

Pure stdlib, no jax import: the launcher's supervisor uses this before
any backend exists, and the server thread only reads in-process state
(never a device or a collective — the TF111 hazard does not apply).
"""

from __future__ import annotations

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

ENV_PORT = "TPUFRAME_METRICS_PORT"
ENV_TEXTFILE = "TPUFRAME_METRICS_TEXTFILE"

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def _escape_label(v) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_sample(name: str, labels: dict, value) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape_label(v)}"'
                        for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {float(value):g}"
    return f"{name} {float(value):g}"


class MetricsExporter:
    """Push-gauges + pull-collectors rendered as one OpenMetrics page.

    ``set_gauge(name, value, **labels)`` stores a sample (the push API
    for per-step facts); ``add_collector(fn)`` registers ``fn() ->
    iterable of (name, labels_dict, value)`` polled at render time (the
    pull API for live state like the goodput meter).  Families whose
    name ends in ``_total`` render as counters (OpenMetrics requires the
    suffix), everything else as gauges.
    """

    def __init__(self, *, port: int | None = None,
                 textfile: str | None = None, health=None):
        self._port_requested = port
        self.port: int | None = None
        self.textfile = textfile
        self._health = health
        self._lock = threading.Lock()
        self._gauges: dict[tuple, float] = {}
        self._collectors: list = []
        self._handlers: dict[str, object] = {}
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- registration ----------------------------------------------------

    def set_gauge(self, name: str, value, **labels) -> None:
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        with self._lock:
            self._gauges[(name, tuple(sorted(labels.items())))] = v

    def add_collector(self, fn) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def set_health(self, fn) -> None:
        with self._lock:
            self._health = fn

    def add_handler(self, path: str, fn) -> None:
        """Register ``fn(body: bytes) -> (status: int, body: bytes)`` to
        serve POST requests at ``path``.  This keeps the process's whole
        HTTP surface on the one sanctioned endpoint (TF113): the serving
        replica's ``/generate`` rides the same server, port knob and
        health probe as the scrape plane instead of standing up its own
        socket."""
        with self._lock:
            self._handlers[path] = fn

    def _handler_for(self, path: str):
        with self._lock:
            return self._handlers.get(path)

    def healthy(self) -> bool:
        fn = self._health
        if fn is None:
            return True
        try:
            return bool(fn())
        except Exception:  # noqa: BLE001 — a broken probe reads unhealthy
            return False

    # -- rendering -------------------------------------------------------

    def _samples(self) -> list[tuple[str, dict, float]]:
        out: list[tuple[str, dict, float]] = []
        try:
            from tpuframe.obs import metrics

            for name, v in sorted(metrics.counters().items()):
                out.append(("tpuframe_events_total", {"name": name},
                            float(v)))
        except Exception:  # noqa: BLE001 — counters are best-effort
            pass
        try:
            from tpuframe.obs import tracing

            # Live leak signal: spans this process opened and has not
            # closed.  A replica stuck with unanswered requests shows a
            # climbing gauge on /metrics long before the offline
            # leaked-span anomaly sweep ever runs.
            out.append(("tpuframe_open_spans", {},
                        float(tracing.open_span_count())))
        except Exception:  # noqa: BLE001 — best-effort like the counters
            pass
        with self._lock:
            gauges = list(self._gauges.items())
            collectors = list(self._collectors)
        for (name, labels), v in gauges:
            out.append((name, dict(labels), v))
        for fn in collectors:
            try:
                for name, labels, v in fn():
                    out.append((str(name), dict(labels or {}), float(v)))
            except Exception:  # noqa: BLE001 — one broken collector must
                continue  # not blank the whole exposition
        return out

    def render(self) -> str:
        by_family: dict[str, list[str]] = {}
        for name, labels, v in self._samples():
            by_family.setdefault(name, []).append(
                _fmt_sample(name, labels, v))
        lines: list[str] = []
        for name in sorted(by_family):
            if name.endswith("_total"):
                lines.append(f"# TYPE {name[:-len('_total')]} counter")
            else:
                lines.append(f"# TYPE {name} gauge")
            lines.extend(sorted(by_family[name]))
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    # -- serving ---------------------------------------------------------

    def start(self) -> "MetricsExporter":
        if self._port_requested is None or self._server is not None:
            return self
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                if self.path.split("?")[0] == "/metrics":
                    body = exporter.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                elif self.path.split("?")[0] == "/healthz":
                    ok = exporter.healthy()
                    body = (b"ok\n" if ok else b"unhealthy\n")
                    self.send_response(200 if ok else 503)
                    self.send_header("Content-Type",
                                     "text/plain; charset=utf-8")
                else:
                    body = b"not found\n"
                    self.send_response(404)
                    self.send_header("Content-Type",
                                     "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path = self.path.split("?")[0]
                handler = exporter._handler_for(path)
                if handler is None:
                    status, body = 404, b"not found\n"
                else:
                    try:
                        n = int(self.headers.get("Content-Length") or 0)
                        status, body = handler(self.rfile.read(n))
                    except Exception as e:  # noqa: BLE001 — a broken
                        # handler must answer 500, not kill the server
                        status, body = 500, f"{type(e).__name__}: {e}\n" \
                            .encode()
                self.send_response(int(status))
                self.send_header("Content-Type",
                                 "application/json; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stdout
                pass

        # Lifecycle fields (_server/port/_thread) are caller-serialized:
        # start()/stop() only run under the module _exporter_lock
        # (start_from_env/stop below), and the handler thread never
        # writes them — so TF114 is suppressed here rather than holding
        # self._lock across bind/serve setup.
        try:
            self._server = ThreadingHTTPServer(  # tf-lint: ok[TF114]
                ("0.0.0.0", int(self._port_requested)), _Handler)
        except OSError as e:
            import sys

            if int(self._port_requested) != 0:
                # Occupied/unbindable port: fall back to an ephemeral one
                # (the bound port lands on ``.port``) instead of silently
                # dropping the scrape plane — a fleet replica without a
                # /healthz is indistinguishable from a dead one.
                try:
                    self._server = ThreadingHTTPServer(  # tf-lint: ok[TF114]
                        ("0.0.0.0", 0), _Handler)
                    print(f"[tpuframe.obs] metrics exporter: cannot bind "
                          f"port {self._port_requested} ({e}) — fell back "
                          f"to ephemeral port "
                          f"{self._server.server_address[1]}",
                          file=sys.stderr)
                except OSError as e2:
                    e = e2
                    self._server = None  # tf-lint: ok[TF114] — caller-ser.
            if self._server is None:
                print(f"[tpuframe.obs] metrics exporter: cannot bind port "
                      f"{self._port_requested} ({e}) — scrape endpoint "
                      f"off, textfile output unaffected", file=sys.stderr)
                return self
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]  # tf-lint: ok[TF114]
        # Serves in-process snapshots only (counters/gauges under a plain
        # lock) — never touches jax or a collective, so the TF111
        # collective-ordering hazard does not apply.
        self._thread = threading.Thread(  # tf-lint: ok[TF111, TF114]
            target=self._server.serve_forever, daemon=True,
            name="tpuframe-metrics")
        self._thread.start()
        return self

    def flush(self) -> None:
        """Rewrite the textfile exposition (atomic), when configured."""
        if not self.textfile:
            return
        try:
            d = os.path.dirname(self.textfile)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{self.textfile}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(self.render())
            os.replace(tmp, self.textfile)
        except OSError:
            pass  # scrape-less fallback is itself best-effort

    def stop(self) -> None:
        # Same caller-serialized lifecycle contract as start(): runs only
        # under the module _exporter_lock, and holding self._lock across
        # shutdown()/join() would stall a mid-scrape handler holding it.
        self.flush()
        server, self._server = self._server, None  # tf-lint: ok[TF114]
        if server is not None:
            try:
                server.shutdown()
                server.server_close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None  # tf-lint: ok[TF114] — caller-serialized


# ---------------------------------------------------------------------------
# Module-level singleton — one exporter per process, env-gated.
# ---------------------------------------------------------------------------

_exporter: MetricsExporter | None = None
_exporter_lock = threading.Lock()


def start_from_env(*, health=None, port_offset: int = 0
                   ) -> MetricsExporter | None:
    """Start (or return) the process-wide exporter.  Off unless
    ``TPUFRAME_METRICS_PORT`` or ``TPUFRAME_METRICS_TEXTFILE`` is set.
    ``port_offset`` shifts the bound port (the launcher's supervisor uses
    +1 so it never collides with a child's bind on the same host)."""
    global _exporter
    with _exporter_lock:
        if _exporter is not None:
            if health is not None and _exporter._health is None:
                _exporter.set_health(health)
            return _exporter
        port_s = os.environ.get(ENV_PORT, "").strip()
        textfile = os.environ.get(ENV_TEXTFILE, "").strip() or None
        if not port_s and not textfile:
            return None
        port: int | None = None
        if port_s:
            try:
                port = int(port_s)
            except ValueError:
                port = None
            else:
                if port and port_offset:
                    port += port_offset
        _exporter = MetricsExporter(port=port, textfile=textfile,
                                    health=health).start()
        return _exporter


def get() -> MetricsExporter | None:
    return _exporter


def stop() -> None:
    global _exporter
    with _exporter_lock:
        if _exporter is not None:
            _exporter.stop()
            _exporter = None
