"""Structured run-event log — the joinable record of what a run *did*.

The reference's observability surface was the Horovod timeline plus rank-0
throughput prints (SURVEY.md §5.1/§5.5); PR 1-3 replaced the timeline with
XLA profiler hooks and grew counters, but steps, restarts, retries, stalls
and compile events still lived in unjoinable stdout lines.  This module is
the structured layer underneath all of them: a process-wide, thread-safe
JSONL writer, one file per host (``events.<host>.jsonl``), every record
carrying a common envelope so one directory of files reconstructs the full
lifecycle of a run — including supervised relaunches, which are stitched
together by the ``attempt`` field the supervisor increments
(``launch/launcher.py:run_with_relaunch`` → ``TPUFRAME_ATTEMPT``).

Record envelope (every line)::

    {"schema": 2, "type": "<event type>", "t": <unix seconds>,
     "host": "<hostname>", "proc": <process index>, "attempt": <int>,
     ...type-specific fields}

Schema history: v2 added the ``input`` goodput bucket (``run_end``'s
``goodput.buckets``) and the optional ``input_wait_ms``/``block_ms``
fields on ``step``/``ckpt_save``.  v1 logs stay readable — the new
fields are additive, so the validator accepts every version in
``ACCEPTED_SCHEMAS`` and the analyzer treats the absent fields as zero.

Event types (see ``REQUIRED_FIELDS`` for the per-type contract):

  ============== ========================================================
  run_start      run manifest: config name+hash, mesh/topology, jax
                 version, tune-DB fingerprint, TPUFRAME_XLA_OPTS,
                 resume step
  step           step index, host wall ms, loss, examples processed
  compile        a compilation observed (first-step wall, or a
                 persistent-cache hit/miss from utils/compile_cache)
  ckpt_save      checkpoint written (step, ms, async?)
  ckpt_restore   checkpoint restored (step, ms)
  retry          a retry-policy attempt fired (op, outcome)
  fault_injected a TPUFRAME_FAULTS seam fired (seam, kind, step)
  stall          heartbeat watchdog fired (last_step, idle_s)
  preempt        SIGTERM/SIGINT preemption observed (signal[, step])
  devmem         HBM telemetry sample (per-device memory_stats)
  remat_policy   rematerialization policy chosen for the step program
                 (policy name, resolution source, predicted bytes)
  weight_update  weight-update sharding mode chosen for the step program
                 (mode replicated|zero1, resolution source, shard count)
  wire_format    gradient-path collective wire format chosen for the
                 step program (format fp|int8-block, resolution source)
  fusion_threshold
                 gradient-fusion bucket threshold chosen for the step
                 program (threshold bytes or null for per-leaf,
                 resolution source env|tune_db|default)
  pspec          declarative parallelism spec the run's mesh was built
                 from (canonical spec string, resolution source)
  elastic_resize world size changed across a relaunch boundary (n_from,
                 n_to, rescale policy + source, old/new batch and LR)
  run_end        final step, wall s, goodput buckets, MFU, counters,
                 peak HBM per device
  trace_start    a jax.profiler trace window opened (step, artifact path)
  trace_end      the trace window closed (step, artifact path)
  serve_step     one continuous-batching scheduler step (active slots,
                 admissions, tokens produced, queue depth)
  serve_request  a served request retired (prompt/output token counts,
                 TTFT/TPOT ms)
  serve_summary  end-of-loadgen rollup (requests, tokens/sec, devices)
  router_admit   the fleet router accepted a request into its bounded
                 pending queue
  router_shed    admission control rejected a request (429-style: the
                 bounded queue was full; queued = depth at rejection)
  router_dispatch
                 a request was sent to a replica (first placement)
  router_hedge   a straggler request got a second, racing dispatch on
                 another replica (first winner kept)
  router_redispatch
                 an in-flight request was re-dispatched off a draining
                 replica (503 / scrape timeout / dispatch failure)
  router_drain   a replica was marked draining (replica, reason) — no
                 new dispatches; its in-flight work is re-dispatched
  router_request a routed request retired at the router (end-to-end
                 TTFT ms, winning replica, output tokens)
  router_summary end-of-run fleet rollup (completed/shed/hedged/
                 redispatched counts, replicas seen)
  rollout_step   the rollout controller moved one replica through one
                 phase of a rolling weight update (replica, target
                 version, phase ∈ drain/swapped/swap_failed/relaunched/
                 readmitted/promoted/rolled_back)
  rollout_done   a rolling update completed: every replica is on the
                 new version (version, replicas, mixed-version window s)
  rollout_abort  the rollout was rolled back — the canary gate caught a
                 regression (version, the failing metric, reason)
  span_open      a trace span opened (trace id, span id, name; parent
                 span id when not a root) — emitted ONLY through
                 obs.tracing, the sanctioned span API (lint TF123)
  span_close     the span closed (trace, span, same-process monotonic
                 duration ms; outcome fields like status/duplicate/
                 ttft_ms ride along)
  span_note      a trace annotation that is not a timed phase (drain
                 re-queue, rollout swap) — trace id + note text,
                 optionally anchored to a span
  ============== ========================================================

Emission is *best-effort everywhere*: ``emit()`` is a no-op until
``init()`` ran, and never raises after ``close()`` — a broken or absent
event log must not take down a retry loop mid-recovery or a signal
handler mid-preemption.

Enable via ``TPUFRAME_EVENTS_DIR=<dir>`` (train.py also takes
``--events-dir``).  Pure stdlib — no jax import; the writer must work in
the launcher/supervisor before any backend exists, and the offline
analyzer (``python -m tpuframe.obs``) must stay light.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time

SCHEMA_VERSION = 2

# Every schema this reader still understands.  Bumping SCHEMA_VERSION
# without keeping the predecessor here strands existing logs (and the
# shipped docs/samples/, which the CI selfcheck validates on purpose).
ACCEPTED_SCHEMAS = (1, 2)

ENV_DIR = "TPUFRAME_EVENTS_DIR"
ENV_ATTEMPT = "TPUFRAME_ATTEMPT"

# Per-type required fields (beyond the envelope); the contract the
# ``--selfcheck`` schema validation and the analyzer both enforce.
REQUIRED_FIELDS: dict[str, tuple[str, ...]] = {
    "run_start": ("config", "config_hash", "jax_version"),
    "step": ("step", "wall_ms"),
    "compile": (),
    "ckpt_save": ("step",),
    "ckpt_restore": ("step",),
    "retry": ("op",),
    "fault_injected": ("seam", "kind"),
    "stall": ("last_step", "idle_s"),
    "preempt": ("signal",),
    "devmem": ("devices",),
    "remat_policy": ("policy", "source"),
    "weight_update": ("mode", "source"),
    "wire_format": ("format", "source"),
    "fusion_threshold": ("threshold", "source"),
    "pspec": ("spec", "source"),
    "elastic_resize": ("n_from", "n_to", "policy"),
    "run_end": ("final_step", "wall_s", "goodput"),
    "trace_start": ("step", "path"),
    "trace_end": ("step", "path"),
    "serve_step": ("step", "wall_ms", "active"),
    "serve_request": ("id", "prompt_tokens", "output_tokens", "ttft_ms"),
    "serve_summary": ("requests", "tokens_per_s"),
    "router_admit": ("id",),
    "router_shed": ("id", "queued"),
    "router_dispatch": ("id", "replica"),
    "router_hedge": ("id", "replica"),
    "router_redispatch": ("id", "replica"),
    "router_drain": ("replica", "reason"),
    "router_request": ("id", "replica", "ttft_ms"),
    "router_summary": ("requests", "shed"),
    "rollout_step": ("replica", "version", "phase"),
    "rollout_done": ("version", "replicas"),
    "rollout_abort": ("version", "metric", "reason"),
    # Span events are additive within schema v2 (old readers never see
    # them unless emitted).  obs.tracing.SPAN_REQUIRED_FIELDS pins the
    # same tuples and trace.check() cross-checks the two copies.
    "span_open": ("trace", "span", "name"),
    "span_close": ("trace", "span", "ms"),
    "span_note": ("trace", "note"),
}

_ENVELOPE = ("schema", "type", "t", "host", "proc", "attempt")

_FILE_RE = re.compile(r"^events\.(?P<host>.+)\.jsonl$")

# In-process tee: every record built by any EventLog is also handed to the
# registered listeners (the flight recorder's hook).  Listeners see the
# record BEFORE the file write and regardless of its outcome — a crash
# that tears the JSONL mid-line must not also lose the in-memory copy.
_listeners: list = []


def add_listener(fn) -> None:
    """Register ``fn(record: dict)`` to observe every emitted record.
    Listener exceptions are swallowed (emission never raises)."""
    if fn not in _listeners:
        _listeners.append(fn)


def remove_listener(fn) -> None:
    try:
        _listeners.remove(fn)
    except ValueError:
        pass


def _notify(record: dict) -> None:
    for fn in list(_listeners):
        try:
            fn(record)
        except Exception:  # noqa: BLE001 — a broken listener must not
            pass  # take down the seam that emitted


def _hostname() -> str:
    try:
        return socket.gethostname().split(".")[0] or "host"
    except OSError:
        return "host"


def _process_index() -> int:
    """Rank without forcing a jax import (the fault-registry pattern):
    the launcher env var is authoritative in the fake cluster; jax is
    consulted only when already imported."""
    env = os.environ.get("TPUFRAME_PROCESS_ID")
    if env:
        return int(env)
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return jax.process_index()
        except Exception:  # noqa: BLE001 — backend not initialized yet
            return 0
    return 0


def attempt_id() -> int:
    """The supervisor-stitched attempt counter (0 on a first launch)."""
    try:
        return int(os.environ.get(ENV_ATTEMPT, "0") or "0")
    except ValueError:
        return 0


class EventLog:
    """Thread-safe JSONL event writer, one file per (host, process).

    The filename doubles as the merge key: ``events.<host>.jsonl`` where
    ``<host>`` is ``<hostname>-p<process index>`` — unique per writer on
    a shared filesystem, reconstructable by the offline merger.  Opened
    in append mode so relaunched attempts extend the same file and the
    analyzer sees one continuous, attempt-tagged stream.
    """

    def __init__(self, directory: str, *, host: str | None = None,
                 proc: int | None = None):
        self.proc = _process_index() if proc is None else proc
        self.host = host or f"{_hostname()}-p{self.proc}"
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"events.{self.host}.jsonl")
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", buffering=1)
        self._closed = False

    def emit(self, etype: str, **fields) -> dict | None:
        """Append one record; returns it (None when the log is closed).
        Never raises: observability must not take down the run."""
        record = {
            "schema": SCHEMA_VERSION,
            "type": etype,
            "t": round(time.time(), 3),
            "host": self.host,
            "proc": self.proc,
            "attempt": attempt_id(),
            **fields,
        }
        try:
            line = json.dumps(record, default=str)
        except (TypeError, ValueError):
            return None
        _notify(record)
        with self._lock:
            if self._closed:
                return None
            try:
                self._fh.write(line + "\n")
            except (OSError, ValueError):
                return None
        return record

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                try:
                    self._fh.close()
                except OSError:
                    pass

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Module-level singleton — the log every instrumented seam writes through.
# ---------------------------------------------------------------------------

_log: EventLog | None = None
_log_lock = threading.Lock()


def init(directory: str | None = None) -> EventLog | None:
    """(Re)open the process-wide event log.  ``directory=None`` consults
    ``TPUFRAME_EVENTS_DIR``; unset/empty means events stay off and every
    ``emit()`` is a cheap no-op."""
    global _log
    directory = directory or os.environ.get(ENV_DIR, "")
    if not directory.strip():
        return None
    with _log_lock:
        if _log is not None:
            _log.close()
        _log = EventLog(directory)
        return _log


def get() -> EventLog | None:
    return _log


def enabled() -> bool:
    return _log is not None


def emit(etype: str, **fields) -> dict | None:
    """Write through the singleton; silent no-op when events are off."""
    log = _log
    if log is None:
        return None
    return log.emit(etype, **fields)


def close() -> None:
    global _log
    with _log_lock:
        if _log is not None:
            _log.close()
            _log = None


# ---------------------------------------------------------------------------
# Reading / validation — the offline half (CLI, tests, CI selfcheck).
# ---------------------------------------------------------------------------

def validate_record(rec: dict) -> list[str]:
    """Problems with one parsed record; empty list means valid."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is not an object: {rec!r:.80}"]
    for key in _ENVELOPE:
        if key not in rec:
            problems.append(f"missing envelope key {key!r}")
    if rec.get("schema") not in ACCEPTED_SCHEMAS:
        problems.append(f"unknown schema version {rec.get('schema')!r} "
                        f"(this reader knows {ACCEPTED_SCHEMAS})")
    etype = rec.get("type")
    if etype in REQUIRED_FIELDS:
        for key in REQUIRED_FIELDS[etype]:
            if key not in rec:
                problems.append(f"{etype} record missing field {key!r}")
    elif etype is not None and etype not in REQUIRED_FIELDS:
        problems.append(f"unknown event type {etype!r}")
    return problems


def read_file(path: str, *, strict: bool = False) -> list[dict]:
    """Parse one events file.  Truncated/garbled trailing lines are
    expected after a crash (the JSONL contract: each durable line is one
    event) and are skipped unless ``strict``."""
    out: list[dict] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                if strict:
                    raise ValueError(f"{path}:{lineno}: unparseable event "
                                     f"line {line!r:.80}")
    return out


def event_files(directory: str) -> list[str]:
    """The ``events.<host>.jsonl`` files under ``directory``, sorted."""
    try:
        names = sorted(os.listdir(directory))
    except (FileNotFoundError, NotADirectoryError):
        if _FILE_RE.match(os.path.basename(directory)):
            return [directory]  # a single file passed directly
        return []
    return [os.path.join(directory, n) for n in names if _FILE_RE.match(n)]


def merge(directory: str) -> list[dict]:
    """All hosts' events, merged into one stream ordered by timestamp
    (ties broken by host then original order — a stable multi-host join,
    the structured replacement for eyeballing N interleaved stdouts)."""
    streams: list[dict] = []
    for path in event_files(directory):
        streams.extend(read_file(path))
    return sorted(streams,
                  key=lambda r: (r.get("t", 0.0), str(r.get("host", ""))))


def validate_files(paths) -> list[str]:
    """Schema-validate whole files (the ``--selfcheck`` surface).
    Strict parsing: a *shipped* sample with a garbled line is a bug even
    though a crashed run's tail is not."""
    problems: list[str] = []
    for path in paths:
        try:
            records = read_file(path, strict=True)
        except (OSError, ValueError) as e:
            problems.append(f"{path}: {e}")
            continue
        if not records:
            problems.append(f"{path}: no events")
        for i, rec in enumerate(records, 1):
            problems += [f"{os.path.basename(path)}:{i}: {p}"
                         for p in validate_record(rec)]
    return problems
