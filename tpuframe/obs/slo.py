"""Tail-latency SLO sentry — multi-window burn rates over the event log.

The fleet's latency story so far is descriptive (percentile summaries,
`obs compare`'s pairwise thresholds); this module makes it *normative*:
declared TTFT/TPOT objectives evaluated as error budgets, SRE-style.

Spec grammar (``TPUFRAME_SLO``, comma-separated)::

    ttft<=800ms@99%          # 99% of requests see TTFT <= 800 ms
    tpot<=50ms@95%           # 95% of decode cadences <= 50 ms/token

A sample *violates* when its value exceeds the threshold; the error
budget is ``1 - objective`` (for @99%, 1% of traffic may violate).  The
**burn rate** over a window is ``violation_rate / budget`` — burn 1.0
spends the budget exactly at the sustainable pace, burn 14.4 exhausts a
30-day budget in ~2 days.

Multi-window evaluation (``TPUFRAME_SLO_WINDOWS``, default
``60:14.4,300:6,3600:1``, pairs of ``window_seconds:max_burn``): each
window slides over the sample stream (event wall-clock ``t``) and
records its worst burn.  The per-window factors ARE the policy — short
windows tolerate high burn (a brief spike is not an incident), long
windows demand burn near 1 (a sustained slow bleed is).  An SLO is
breached when ANY window's worst burn exceeds its factor — the classic
fast-burn/slow-burn alert pair generalized to N windows.

TTFT samples come from ``router_request.ttft_ms`` (queue-inclusive, the
number users feel) with ``serve_request`` as the single-replica
fallback; TPOT from ``serve_request.tpot_ms``.

rc contract (``python -m tpuframe.obs slo``, same shape as ``obs
compare``): 0 every SLO met, 1 any breached, 2 no data — so CI can gate
on the sentry exactly like it gates on the comparison.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass

ENV_SLO = "TPUFRAME_SLO"
ENV_WINDOWS = "TPUFRAME_SLO_WINDOWS"

# Generous CPU-fleet defaults: the chaos tier's 3-replica FakeEngine
# fleet under kill/rollout faults stays well inside these (PERF §27);
# a real deployment declares its own via TPUFRAME_SLO.
DEFAULT_SLO = "ttft<=1500ms@99%,tpot<=300ms@95%"
DEFAULT_WINDOWS = "60:14.4,300:6,3600:1"

_SPEC_RE = re.compile(
    r"^\s*(ttft|tpot)\s*<=\s*([0-9]+(?:\.[0-9]+)?)\s*ms\s*"
    r"@\s*([0-9]+(?:\.[0-9]+)?)\s*%?\s*$", re.IGNORECASE)


@dataclass(frozen=True)
class SLO:
    """One declared objective: ``metric <= threshold_ms`` for at least
    ``objective`` (fraction) of samples."""

    metric: str          # "ttft" | "tpot"
    threshold_ms: float
    objective: float     # e.g. 0.99

    def __str__(self) -> str:
        return (f"{self.metric}<={self.threshold_ms:g}ms"
                f"@{100.0 * self.objective:g}%")


def parse_slos(text: str) -> list[SLO]:
    """Parse the comma-separated spec grammar; raises ValueError on any
    malformed clause — a silently-dropped SLO is a sentry that lies."""
    slos: list[SLO] = []
    for clause in str(text).split(","):
        if not clause.strip():
            continue
        m = _SPEC_RE.match(clause)
        if m is None:
            raise ValueError(
                f"bad SLO clause {clause.strip()!r} — want e.g. "
                f"'ttft<=800ms@99%'")
        pct = float(m.group(3))
        if not 0.0 < pct < 100.0:
            raise ValueError(f"SLO objective {pct}% outside (0, 100)")
        slos.append(SLO(metric=m.group(1).lower(),
                        threshold_ms=float(m.group(2)),
                        objective=pct / 100.0))
    if not slos:
        raise ValueError("empty SLO spec")
    return slos


def parse_windows(text: str) -> list[tuple[float, float]]:
    """``"60:14.4,300:6"`` -> ``[(60.0, 14.4), (300.0, 6.0)]``."""
    out: list[tuple[float, float]] = []
    for clause in str(text).split(","):
        if not clause.strip():
            continue
        try:
            w, f = clause.split(":")
            window_s, factor = float(w), float(f)
        except ValueError:
            raise ValueError(
                f"bad SLO window {clause.strip()!r} — want "
                f"'window_seconds:max_burn'") from None
        if window_s <= 0 or factor <= 0:
            raise ValueError(f"SLO window {clause.strip()!r} must be "
                             f"positive")
        out.append((window_s, factor))
    if not out:
        raise ValueError("empty SLO window spec")
    return out


def resolve_slos() -> list[SLO]:
    return parse_slos(os.environ.get(ENV_SLO, "").strip() or DEFAULT_SLO)


def resolve_windows() -> list[tuple[float, float]]:
    return parse_windows(os.environ.get(ENV_WINDOWS, "").strip()
                         or DEFAULT_WINDOWS)


def _samples(events: list, metric: str) -> list[tuple[float, float]]:
    """(wall t, value ms) samples for one metric, time-ordered.  TTFT
    prefers the router's queue-inclusive number; a single-replica log
    with no router falls back to ``serve_request``."""
    out: list[tuple[float, float]] = []
    if metric == "ttft":
        out = [(float(r.get("t") or 0.0), float(r["ttft_ms"]))
               for r in events if r.get("type") == "router_request"
               and r.get("ttft_ms") is not None]
        if not out:
            out = [(float(r.get("t") or 0.0), float(r["ttft_ms"]))
                   for r in events if r.get("type") == "serve_request"
                   and r.get("ttft_ms") is not None]
    elif metric == "tpot":
        out = [(float(r.get("t") or 0.0), float(r["tpot_ms"]))
               for r in events if r.get("type") == "serve_request"
               and r.get("tpot_ms") is not None]
    out.sort(key=lambda s: s[0])
    return out


def _worst_burn(samples: list[tuple[float, float]], threshold_ms: float,
                budget: float, window_s: float) -> tuple[float, int]:
    """Max burn rate over every window anchored at a sample, plus the
    sample count of that worst window.  Two-pointer sweep — O(n)."""
    worst, worst_n = 0.0, 0
    lo = 0
    bad_in = 0
    for hi in range(len(samples)):
        if samples[hi][1] > threshold_ms:
            bad_in += 1
        while samples[hi][0] - samples[lo][0] > window_s:
            if samples[lo][1] > threshold_ms:
                bad_in -= 1
            lo += 1
        n = hi - lo + 1
        burn = (bad_in / n) / budget
        if burn > worst or (burn == worst and n > worst_n):
            worst, worst_n = burn, n
    return worst, worst_n


def evaluate(events: list, slos: list[SLO] | None = None,
             windows: list[tuple[float, float]] | None = None) -> dict:
    """Evaluate every SLO over the stream.  Returns::

        {"rc": 0|1|2, "slos": [{"slo", "metric", "samples",
                                "violations", "breached",
                                "windows": [{"window_s", "max_burn",
                                             "burn", "n", "breached"}]}]}

    rc 2 only when NO declared SLO saw a single sample (an empty log
    must not read as "SLOs met").
    """
    slos = resolve_slos() if slos is None else slos
    windows = resolve_windows() if windows is None else windows
    rows = []
    any_data, any_breach = False, False
    for slo in slos:
        samples = _samples(events, slo.metric)
        budget = max(1.0 - slo.objective, 1e-9)
        row = {"slo": str(slo), "metric": slo.metric,
               "samples": len(samples),
               "violations": sum(1 for _t, v in samples
                                 if v > slo.threshold_ms),
               "breached": None, "windows": []}
        if samples:
            any_data = True
            breached = False
            for window_s, factor in windows:
                burn, n = _worst_burn(samples, slo.threshold_ms,
                                      budget, window_s)
                hit = burn > factor
                breached = breached or hit
                row["windows"].append({
                    "window_s": window_s, "max_burn": factor,
                    "burn": round(burn, 3), "n": n, "breached": hit})
            row["breached"] = breached
            any_breach = any_breach or breached
        rows.append(row)
    rc = 2 if not any_data else (1 if any_breach else 0)
    return {"rc": rc, "slos": rows}
