"""``python -m tpuframe.obs`` — offline analyzer over structured event logs.

Subcommands (all take a directory of ``events.<host>.jsonl`` files, or a
single file):

  summarize  merged goodput breakdown (bucket seconds + % of wall),
             step-time distribution, MFU + HBM-roofline utilization,
             chosen remat policy, peak HBM, run_end counters.
             ``--selfcheck`` instead schema-validates shipped/sample
             event files (the analysis CI gate calls this).
  merge      one time-ordered multi-host stream to stdout or ``-o``.
  anomalies  step-time regressions vs. a rolling median, heartbeat
             stalls, retry storms, low MFU, attempts with no run_end,
             steps blocked on the input pipeline or on a checkpoint
             save beyond ``--blocked-ms``, and attempts whose goodput
             buckets fail the sums-to-wall invariant.
             Exits 1 when anything is flagged (scriptable).
  compare    the regression sentry: diff run B against baseline A on
             step-time p50/p90, productive goodput fraction, MFU and
             serve TTFT/TPOT p90 against thresholds; exits 1 when B
             regressed.  With the on-chip relay down, this is how two
             runs' profiles are proven same-or-better offline.
  trace      per-request waterfalls from the tracing plane's span
             events: reconstructs every trace from the merged
             multi-process stream, renders the slowest (or a named
             --trace/--rid) as an indented waterfall with the critical
             path, and verifies the completeness contract — every
             admitted rid resolves to exactly one complete root span,
             no orphan/leaked spans, phase sums match the recorded
             queue-inclusive TTFT within --tol-ms.  Exits 1 on any
             trace anomaly.
  slo        the tail-latency SLO sentry: evaluates declared TTFT/TPOT
             objectives (--slo / TPUFRAME_SLO) with multi-window burn
             rates (--windows / TPUFRAME_SLO_WINDOWS) over the event
             stream.  Exits 0 all met / 1 breached / 2 no data — the
             same rc contract as ``compare``.

Examples::

    python -m tpuframe.obs summarize /runs/r7/events
    python -m tpuframe.obs anomalies /runs/r7/events --mfu-min 0.3
    python -m tpuframe.obs merge /runs/r7/events -o merged.jsonl
    python -m tpuframe.obs compare /runs/baseline /runs/candidate
    python -m tpuframe.obs trace /runs/fleet/events --slowest 3
    python -m tpuframe.obs slo /runs/fleet/events --slo 'ttft<=800ms@99%'
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpuframe.obs import events as events_lib
from tpuframe.obs import goodput as goodput_lib
from tpuframe.obs import slo as slo_lib
from tpuframe.obs import tracing


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.2f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n:.2f} GiB"


def _load(directory: str) -> list[dict]:
    files = events_lib.event_files(directory)
    if not files:
        print(f"[obs] no events.<host>.jsonl under {directory}",
              file=sys.stderr)
        raise SystemExit(2)
    return events_lib.merge(directory)


def _sample_paths() -> list[str]:
    """The repo-shipped sample event files (docs/samples/) — the
    selfcheck's default target, so a schema change that strands old logs
    fails CI before it ships.  One run per directory: subdirectories
    hold separate runs (e.g. ``samples/serve/``) that must validate but
    must NOT merge into the training run's attempt timeline."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    base = os.path.join(root, "docs", "samples")
    paths = events_lib.event_files(base)
    try:
        subdirs = sorted(os.listdir(base))
    except (FileNotFoundError, NotADirectoryError):
        subdirs = []
    for name in subdirs:
        sub = os.path.join(base, name)
        if os.path.isdir(sub):
            paths.extend(events_lib.event_files(sub))
    return paths


def _samples_root() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "docs", "samples")


def _selfcheck_compare() -> list[str]:
    """The regression sentry's own golden test: the shipped fast/slow
    pair must flag as a regression, and the identical pair must not —
    a threshold or percentile change that breaks either direction fails
    CI here before it ships."""
    fast = os.path.join(_samples_root(), "compare_fast")
    slow = os.path.join(_samples_root(), "compare_slow")
    if not (events_lib.event_files(fast) and events_lib.event_files(slow)):
        return [f"compare golden pair missing under {_samples_root()} "
                f"(compare_fast/ + compare_slow/)"]
    problems: list[str] = []
    a, b = events_lib.merge(fast), events_lib.merge(slow)
    flagged = goodput_lib.compare_runs(a, b)
    if not flagged["regressions"]:
        problems.append("compare(fast, slow) flagged no regression — the "
                        "sentry is blind")
    clean = goodput_lib.compare_runs(a, a)
    for r in clean["regressions"]:
        problems.append(f"compare(fast, fast) flagged {r['metric']} — "
                        f"the sentry false-positives on identity")
    return problems


def _selfcheck_trace() -> list[str]:
    """The tracing plane's golden test: the shipped traced-fleet sample
    (a real 2-replica fleet run) must reconstruct whole — every admitted
    rid to one complete root, zero orphans/leaks, phase sums matching
    the recorded TTFT."""
    sample = os.path.join(_samples_root(), "traced_fleet")
    if not events_lib.event_files(sample):
        return [f"traced-fleet golden sample missing under {sample}"]
    merged = events_lib.merge(sample)
    problems = [f"traced_fleet: [{p['kind']}] {p['detail']}"
                for p in tracing.verify_traces(merged)]
    traces = tracing.build_traces(merged)
    if not any(tv.complete_roots() for tv in traces.values()):
        problems.append("traced_fleet: no complete request root "
                        "reconstructed")
    return problems


def cmd_selfcheck(directory: str | None) -> int:
    paths = (events_lib.event_files(directory) if directory
             else _sample_paths())
    if not paths:
        print("[obs] selfcheck: no event files found", file=sys.stderr)
        return 1
    problems = events_lib.validate_files(paths)
    if directory is None:
        # Default (shipped-samples) mode also proves the compare sentry
        # against its golden pair and the trace reconstructor against
        # the traced-fleet sample.
        problems += _selfcheck_compare()
        problems += _selfcheck_trace()
    for p in problems:
        print(f"OBS {p}")
    print(f"[obs] selfcheck: {len(paths)} file(s), "
          f"{len(problems)} problem(s)")
    return 1 if problems else 0


def cmd_summarize(directory: str, generation: str | None) -> int:
    merged = _load(directory)
    summary = goodput_lib.from_events(merged, generation=generation)
    hosts = sorted({r.get("host", "?") for r in merged})
    start = next((r for r in merged if r.get("type") == "run_start"), None)

    print(f"run: {len(merged)} events, {len(hosts)} host file(s), "
          f"{summary['attempts']} attempt(s)")
    if start is not None:
        print(f"  config={start.get('config')} "
              f"hash={start.get('config_hash', '')[:12]} "
              f"jax={start.get('jax_version')} "
              f"devices={start.get('devices')} mesh={start.get('mesh')}")

    buckets = summary["buckets"]
    wall = summary["wall_s"] or 1e-9
    print(f"goodput breakdown (wall {summary['wall_s']:.1f}s, "
          f"{summary['steps']} steps, final step "
          f"{summary.get('final_step', 0)}):")
    for name in goodput_lib.BUCKETS:
        sec = buckets.get(name, 0.0)
        print(f"  {name:<11} {sec:9.2f}s  {100.0 * sec / wall:5.1f}%")
    if summary["attempts"] > 1:
        print(f"  restart-lost {summary['restart_lost_s']:.2f}s across "
              f"{summary['attempts']} attempts "
              f"({summary['retrained_steps']} steps retrained)")
    if summary.get("elastic_resizes"):
        print(f"  elastic resizes: "
              f"{', '.join(summary['elastic_transitions'])} devices")

    times = sorted(goodput_lib.step_times_ms(merged))
    if times:
        mean = sum(times) / len(times)
        print(f"step time (ms, {len(times)} post-compile steps): "
              f"mean={mean:.2f} p50={_percentile(times, 0.5):.2f} "
              f"p90={_percentile(times, 0.9):.2f} max={times[-1]:.2f}")

    for key in ("mfu_productive", "mfu_goodput", "hbm_util_productive"):
        if summary.get(key) is not None:
            print(f"{key}: {summary[key]:.4%}")
    remat = next((r for r in reversed(merged)
                  if r.get("type") == "remat_policy"), None)
    if remat is not None:
        pred = remat.get("predicted_bytes_per_step")
        pred_s = f", predicted {_fmt_bytes(int(pred))}/step" if pred else ""
        print(f"remat policy: {remat.get('policy')} "
              f"(source: {remat.get('source')}{pred_s})")
    if summary.get("peak_hbm_bytes") is not None:
        print(f"peak HBM per device: "
              f"{_fmt_bytes(summary['peak_hbm_bytes'])}")

    end = next((r for r in reversed(merged)
                if r.get("type") == "run_end"), None)
    if end and end.get("counters"):
        print("counters at run_end:")
        for k, v in sorted(end["counters"].items()):
            print(f"  {k} = {v}")

    serve = goodput_lib.serve_stats(merged)
    if serve is not None:
        print(f"serving: {serve['requests']} request(s), "
              f"{serve['steps']} step(s), "
              f"{serve['output_tokens']} output token(s)")
        for key, label in (("ttft_ms", "TTFT"), ("tpot_ms", "TPOT")):
            pcts = serve[key]
            if pcts:
                print(f"  {label} (ms): " + " ".join(
                    f"{q}={pcts[q]:.2f}" for q in ("p50", "p90", "p99")))
        if serve["tokens_per_s"] is not None:
            print(f"  tokens/s: {serve['tokens_per_s']:.2f} "
                  f"({serve['tokens_per_s_per_chip']:.2f} per chip, "
                  f"{serve['n_devices']} device(s))")

    fleet = goodput_lib.fleet_stats(merged)
    if fleet is not None:
        print(f"fleet: {fleet['requests']}/{fleet['admitted']} admitted "
              f"request(s) retired, {fleet['shed']} shed, "
              f"{fleet['lost']} lost, {fleet['hedged']} hedged, "
              f"{fleet['redispatched']} redispatched")
        if fleet["by_replica"]:
            print("  by replica: " + " ".join(
                f"{k}={v}" for k, v in fleet["by_replica"].items()))
        for d in fleet["drains"]:
            print(f"  drain: {d['replica']} ({d['reason']})")
        if fleet["ttft_ms"]:
            pcts = fleet["ttft_ms"]
            print("  router TTFT (ms): " + " ".join(
                f"{q}={pcts[q]:.2f}" for q in ("p50", "p90", "p99")))
        if fleet.get("ttft_exemplars"):
            # Exemplars: the actual request behind each percentile row —
            # "p99 regressed" becomes "obs trace --trace <id>".
            for q, ex in fleet["ttft_exemplars"].items():
                tid = ex.get("trace")
                link = f"trace {tid}" if tid else "untraced"
                print(f"  {q} exemplar: rid {ex.get('id')} "
                      f"({ex['ttft_ms']:.2f} ms, {link})")
    return 0


def cmd_merge(directory: str, out: str | None) -> int:
    merged = _load(directory)
    fh = open(out, "w") if out else sys.stdout
    try:
        for rec in merged:
            fh.write(json.dumps(rec) + "\n")
    finally:
        if out:
            fh.close()
            print(f"[obs] merged {len(merged)} events -> {out}",
                  file=sys.stderr)
    return 0


def cmd_anomalies(directory: str, args) -> int:
    merged = _load(directory)
    findings = goodput_lib.find_anomalies(
        merged, slow_factor=args.slow_factor, window=args.window,
        retry_storm=args.retry_storm, mfu_min=args.mfu_min,
        blocked_ms=args.blocked_ms)
    for f in findings:
        print(f"ANOMALY [{f['kind']}] {f['detail']}")
    print(f"[obs] anomalies: {len(findings)} finding(s)")
    return 1 if findings else 0


def cmd_compare(args) -> int:
    a = _load(args.a)
    b = _load(args.b)
    thresholds = {
        "step_pct": args.step_pct,
        "productive_drop": args.prod_drop,
        "mfu_drop": args.mfu_drop,
        "serve_pct": args.serve_pct,
    }
    result = goodput_lib.compare_runs(a, b, thresholds=thresholds,
                                      generation=args.gen)
    if not result["metrics"]:
        print("[obs] compare: no overlapping metrics between the two runs",
              file=sys.stderr)
        return 2
    print(f"compare: baseline={args.a} candidate={args.b}")
    for name, m in sorted(result["metrics"].items()):
        delta = m.get("delta_pct")
        delta_s = (f"{delta:+.1f}%" if delta is not None
                   else f"{m.get('delta', m.get('delta_rel', 0.0)):+.4f}")
        print(f"  {name:<20} A={m['a']:<12.4g} B={m['b']:<12.4g} {delta_s}")
    for r in result["regressions"]:
        print(f"COMPARE-REGRESSION [{r['metric']}] {r['detail']}")
    for r in result["improvements"]:
        print(f"compare-improvement [{r['metric']}] "
              f"{r['a']} -> {r['b']}")
    print(f"[obs] compare: {len(result['regressions'])} regression(s), "
          f"{len(result['improvements'])} improvement(s), "
          f"{len(result['metrics'])} metric(s) compared")
    return 1 if result["regressions"] else 0


def _span_label(sp) -> str:
    fields = dict(sp.opened or {})
    fields.update(sp.closed or {})
    extras = []
    for key in ("replica", "cause", "status", "rid", "tokens"):
        if fields.get(key) is not None:
            extras.append(f"{key}={fields[key]}")
    if fields.get("duplicate"):
        extras.append("duplicate")
    name = sp.name or "?"
    return f"{name}" + (f" [{' '.join(extras)}]" if extras else "")


def _print_trace(tid: str, tv, root) -> None:
    total_ms = root.ms or 0.0
    head = f"trace {tid}"
    if root.closed is not None:
        head += (f": total {total_ms:.2f} ms, "
                 f"ttft {float(root.closed.get('ttft_ms') or 0):.2f} ms")
    else:
        head += ": INCOMPLETE (root never closed)"
    print(head)
    t0 = float((root.opened or {}).get("t") or 0.0)
    width = 40
    for row in tracing.waterfall(root):
        sp = row["span"]
        label = "  " * row["depth"] + _span_label(sp)
        off_ms = 1e3 * max(0.0, float((sp.opened or {}).get("t") or t0)
                           - t0)
        if sp.ms is None:
            print(f"  {label:<36} |{'?' * width}| OPEN "
                  f"(+{off_ms:.1f} ms, never closed)")
            continue
        if total_ms > 0:
            start = int(width * min(1.0, off_ms / total_ms))
            span_w = max(1, int(round(width * min(1.0,
                                                  sp.ms / total_ms))))
            bar = (" " * start + "#" * min(span_w, width - start)
                   ).ljust(width)
        else:
            bar = "#".ljust(width)
        print(f"  {label:<36} |{bar}| {sp.ms:.2f} ms "
              f"(+{off_ms:.1f})")
    for rec in tv.notes:
        print(f"  note: {rec.get('note')} "
              + " ".join(f"{k}={rec[k]}" for k in ("replica", "reason")
                         if rec.get(k) is not None))
    path = tracing.critical_path(root)
    print("  critical path: " + " -> ".join(
        f"{sp.name}({sp.ms:.1f}ms)" if sp.ms is not None
        else f"{sp.name}(open)" for sp in path))


def cmd_trace(args) -> int:
    merged = _load(args.dir)
    traces = tracing.build_traces(merged)
    problems = tracing.verify_traces(merged, tol_ms=args.tol_ms)
    roots = []
    for tid, tv in traces.items():
        for sp in tv.roots:
            if sp.name == "request":
                roots.append((tid, tv, sp))
    complete = [x for x in roots if x[2].complete]
    print(f"traces: {len(traces)} trace(s), {len(roots)} request "
          f"root(s), {len(complete)} complete")
    want_tid = args.trace or getattr(args, "trace_id", None)
    if want_tid is not None:
        selected = [x for x in roots if x[0] == want_tid]
        if not selected:
            print(f"[obs] trace: no trace {want_tid!r} in this stream",
                  file=sys.stderr)
            return 2
    elif args.rid is not None:
        tid = tracing.trace_of(merged, args.rid)
        selected = [x for x in roots if x[0] == tid]
        if not selected:
            print(f"[obs] trace: rid {args.rid} has no trace (unsampled "
                  f"or never admitted)", file=sys.stderr)
            return 2
    else:
        selected = sorted(complete,
                          key=lambda x: -(x[2].ms or 0.0))[:args.slowest]
    for tid, tv, root in selected:
        _print_trace(tid, tv, root)
    for pr in problems:
        print(f"TRACE-ANOMALY [{pr['kind']}] {pr['detail']}")
    print(f"[obs] trace: {len(problems)} anomaly(s)")
    return 1 if problems else 0


def cmd_slo(args) -> int:
    merged = _load(args.dir)
    try:
        slos = (slo_lib.parse_slos(args.slo) if args.slo
                else slo_lib.resolve_slos())
        windows = (slo_lib.parse_windows(args.windows) if args.windows
                   else slo_lib.resolve_windows())
    except ValueError as e:
        print(f"[obs] slo: {e}", file=sys.stderr)
        return 2
    result = slo_lib.evaluate(merged, slos, windows)
    for row in result["slos"]:
        status = ("NO DATA" if row["breached"] is None
                  else "BREACHED" if row["breached"] else "met")
        print(f"SLO {row['slo']}: {status} ({row['samples']} sample(s), "
              f"{row['violations']} violation(s))")
        for w in row["windows"]:
            mark = "BREACH" if w["breached"] else "ok"
            print(f"  window {w['window_s']:g}s: worst burn "
                  f"{w['burn']:.3f} over {w['n']} sample(s) "
                  f"(max {w['max_burn']:g}) {mark}")
    print(f"[obs] slo: rc {result['rc']}")
    return result["rc"]


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="python -m tpuframe.obs",
                                description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("summarize", help="goodput/MFU/step-time summary")
    sp.add_argument("dir", nargs="?", default=None,
                    help="directory of events.<host>.jsonl files")
    sp.add_argument("--gen", default=None,
                    help="TPU generation for MFU recompute (default: the "
                         "run manifest's, else v5e)")
    sp.add_argument("--selfcheck", action="store_true",
                    help="schema-validate event files (shipped samples "
                         "when no dir given) instead of summarizing")

    mp = sub.add_parser("merge", help="time-ordered multi-host merge")
    mp.add_argument("dir")
    mp.add_argument("-o", "--out", default=None)

    ap = sub.add_parser("anomalies", help="flag suspicious run shapes")
    ap.add_argument("dir")
    ap.add_argument("--slow-factor", type=float, default=3.0,
                    help="step regression threshold vs rolling median")
    ap.add_argument("--window", type=int, default=16,
                    help="rolling-median window (steps)")
    ap.add_argument("--retry-storm", type=int, default=5,
                    help="retries within 60s that count as a storm")
    ap.add_argument("--mfu-min", type=float, default=None,
                    help="flag MFU below this fraction (off by default)")
    ap.add_argument("--blocked-ms", type=float, default=1000.0,
                    help="flag steps blocked on input or checkpoint "
                         "saves beyond this many ms (default 1000)")

    cp = sub.add_parser("compare",
                        help="regression sentry: diff run B vs baseline A")
    cp.add_argument("a", help="baseline run's events directory")
    cp.add_argument("b", help="candidate run's events directory")
    cp.add_argument("--step-pct", type=float,
                    default=goodput_lib.DEFAULT_COMPARE_THRESHOLDS[
                        "step_pct"],
                    help="step-time p50/p90 increase (%%) that regresses")
    cp.add_argument("--prod-drop", type=float,
                    default=goodput_lib.DEFAULT_COMPARE_THRESHOLDS[
                        "productive_drop"],
                    help="absolute productive-fraction drop that regresses")
    cp.add_argument("--mfu-drop", type=float,
                    default=goodput_lib.DEFAULT_COMPARE_THRESHOLDS[
                        "mfu_drop"],
                    help="relative MFU drop (fraction) that regresses")
    cp.add_argument("--serve-pct", type=float,
                    default=goodput_lib.DEFAULT_COMPARE_THRESHOLDS[
                        "serve_pct"],
                    help="serve TTFT/TPOT p90 increase (%%) that regresses")
    cp.add_argument("--gen", default=None,
                    help="TPU generation for MFU recompute")

    tp = sub.add_parser("trace",
                        help="per-request waterfalls + completeness "
                             "verification from span events")
    tp.add_argument("dir", help="events directory of a traced fleet run")
    tp.add_argument("trace_id", nargs="?", default=None,
                    help="render this trace id (paste from a summary "
                         "exemplar row); default: the slowest")
    tp.add_argument("--trace", default=None,
                    help="render this trace id (default: the slowest)")
    tp.add_argument("--rid", type=int, default=None,
                    help="render the trace of this router rid")
    tp.add_argument("--slowest", type=int, default=3,
                    help="how many slowest traces to render (default 3)")
    tp.add_argument("--tol-ms", type=float, default=5.0,
                    help="phase-sum vs recorded-TTFT tolerance (ms)")

    lp = sub.add_parser("slo",
                        help="tail-latency SLO sentry (multi-window "
                             "burn rates); rc 0 met / 1 breach / 2 no "
                             "data")
    lp.add_argument("dir", help="events directory to evaluate")
    lp.add_argument("--slo", default=None,
                    help="objectives, e.g. 'ttft<=800ms@99%%,"
                         "tpot<=50ms@95%%' (default: TPUFRAME_SLO or "
                         f"'{slo_lib.DEFAULT_SLO}')")
    lp.add_argument("--windows", default=None,
                    help="window_s:max_burn pairs (default: "
                         "TPUFRAME_SLO_WINDOWS or "
                         f"'{slo_lib.DEFAULT_WINDOWS}')")

    args = p.parse_args(argv)
    if args.cmd == "summarize":
        if args.selfcheck:
            return cmd_selfcheck(args.dir)
        if args.dir is None:
            p.error("summarize needs a directory (or --selfcheck)")
        return cmd_summarize(args.dir, args.gen)
    if args.cmd == "merge":
        return cmd_merge(args.dir, args.out)
    if args.cmd == "compare":
        return cmd_compare(args)
    if args.cmd == "trace":
        return cmd_trace(args)
    if args.cmd == "slo":
        return cmd_slo(args)
    return cmd_anomalies(args.dir, args)


if __name__ == "__main__":
    sys.exit(main())
