"""HBM telemetry — periodic ``memory_stats()`` sampling into the event log.

TPU runtimes expose per-device allocator stats through
``jax.local_devices()[i].memory_stats()`` (``bytes_in_use``,
``peak_bytes_in_use``, ``bytes_limit``...).  A background sampler
records them as ``devmem`` events so a creeping HBM leak or a
fragmentation cliff is visible in the run record, and the peak lands
in the run_end summary next to MFU.

Guarded everywhere: CPU backends and older jax return ``None`` (or
raise) from ``memory_stats()`` — the sampler then never emits and the
peak summary is empty, by design (the "no-op on CPU" contract,
tests/test_observability.py).  jax is imported lazily so this module
stays importable in the stdlib-only analyzer.
"""

from __future__ import annotations

import threading

# The stats keys worth recording when present (allocator-dependent).
_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
         "largest_free_block_bytes")


def sample() -> list[dict] | None:
    """One snapshot: ``[{"id": ..., "bytes_in_use": ...}, ...]`` per
    local device, or None when the backend has no memory stats
    (CPU, old jax) — callers emit nothing in that case."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no backend yet / import race
        return None
    out = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — older jax raises instead of None
            return None
        if not stats:
            return None
        entry = {"id": int(d.id)}
        for key in _KEYS:
            if key in stats:
                entry[key] = int(stats[key])
        out.append(entry)
    return out or None


class DevmemSampler:
    """Background thread sampling every ``interval_s`` into ``emit_fn``
    (normally ``events.emit``), tracking per-device peaks for run_end.

    ``start()`` probes once synchronously: when the backend has no
    memory stats the thread is never started at all — zero overhead on
    CPU test runs.
    """

    def __init__(self, *, interval_s: float = 30.0, emit_fn=None):
        from tpuframe.obs import events

        self.interval_s = interval_s
        self.emit_fn = emit_fn or (lambda **kw: events.emit("devmem", **kw))
        self.active = False
        self._peaks: dict[int, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _record(self, devices: list[dict]) -> None:
        with self._lock:
            for dev in devices:
                seen = dev.get("peak_bytes_in_use", dev.get("bytes_in_use"))
                if seen is not None:
                    did = dev["id"]
                    self._peaks[did] = max(self._peaks.get(did, 0),
                                           int(seen))

    def start(self) -> "DevmemSampler":
        first = sample()
        if first is None:
            return self  # no stats on this backend: stay inert
        self.active = True
        self._record(first)
        self.emit_fn(devices=first)
        # Reads device.memory_stats() only — a local PJRT query, never a
        # collective — so the TF111 ordering hazard does not apply.
        self._thread = threading.Thread(target=self._watch, daemon=True,  # tf-lint: ok[TF111]
                                        name="tpuframe-devmem")
        self._thread.start()
        return self

    def _watch(self) -> None:
        while not self._stop.wait(self.interval_s):
            devices = sample()
            if devices is None:
                continue
            self._record(devices)
            self.emit_fn(devices=devices)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def peak_summary(self) -> dict:
        """``{"peak_hbm_bytes": max-over-devices, "per_device": {...}}``
        — empty dict when nothing was ever sampled (CPU)."""
        with self._lock:
            if not self._peaks:
                return {}
            return {
                "peak_hbm_bytes": max(self._peaks.values()),
                "per_device": {str(k): v
                               for k, v in sorted(self._peaks.items())},
            }
