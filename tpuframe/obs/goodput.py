"""Goodput & MFU accounting — "fast as the hardware allows", verified.

The MLPerf TPU-pod scaling work (arXiv:1909.09756) reports MFU/step-time
accounting as the north-star efficiency metric; ROADMAP's claim is
unverifiable without it.  This module splits a run's wall clock into
named buckets and turns step time into an MFU estimate against the
roofline hardware tables (``tune/roofline.py`` — the same peaks every
PERF.md roofline and bench.py's MFU column use, so the three can never
disagree).

Buckets (seconds; they partition attempt wall time):

  init        process start → first step dispatched (harness build,
              data/restore — includes ckpt_restore time)
  compile     the first train step's wall time (XLA compile + one step;
              host-side the two are indistinguishable, and the compile
              dominates by orders of magnitude)
  productive  steps 2..N — the only bucket that moves the loss
  input       time the train loop sat blocked on the data pipeline
              (``next(data_iter)`` / the prefetch queue's ``q.get()``) —
              the MLPerf-pod scaling work's "input stall" number
              (arXiv:1909.09756), split out of step time in schema v2
  ckpt        blocking checkpoint time (async saves cost only their
              snapshot slice)
  eval        eval passes (incl. the eval program's first compile)
  stall       watchdog-detected dead time (heartbeat ``stall`` events)
  other       wall − sum(above): logging, GC, supervisor glue

Restart-lost time is a *cross-attempt* fact: the analyzer computes it
when stitching attempts — (crashed attempt's time past its last
committed step) + (gap until the relaunch's first event).  A single
attempt cannot know it died.

Two MFU flavors are reported: ``mfu_productive`` (model flops / peak,
over productive step time — the kernel-efficiency number) and
``mfu_goodput`` (over total wall — the fleet-efficiency number; the gap
between the two is exactly the non-productive buckets).

Pure stdlib + ``tune.roofline`` (itself stdlib); both the live meter in
train.py and the offline analyzer share these definitions, so the
run_end summary and ``python -m tpuframe.obs summarize`` can never
drift apart.
"""

from __future__ import annotations

import time

from tpuframe.tune import roofline

BUCKETS = ("init", "compile", "productive", "input", "ckpt", "eval",
           "stall", "other")

DEFAULT_GENERATION = "v5e"


class GoodputMeter:
    """Live bucket accounting for one attempt (train.py's half).

    The loop charges named buckets as it goes; ``summary()`` closes the
    books — ``other`` absorbs the unattributed remainder so the buckets
    always sum to wall time exactly (the analyzer asserts this).
    ``clock`` is injectable for the fake-clock tests.
    """

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._t0 = clock()
        self._buckets = {b: 0.0 for b in BUCKETS if b != "other"}
        self.steps = 0
        self.first_step_s: float | None = None

    def charge(self, bucket: str, seconds: float) -> None:
        if bucket not in self._buckets:
            raise ValueError(f"unknown goodput bucket {bucket!r}; "
                             f"have {sorted(self._buckets)}")
        self._buckets[bucket] += max(0.0, seconds)

    def step(self, seconds: float) -> None:
        """Charge one training step.  The first step is the compile."""
        if self.first_step_s is None:
            self.first_step_s = seconds
            self.charge("compile", seconds)
        else:
            self.charge("productive", seconds)
        self.steps += 1

    def wall_s(self) -> float:
        return self._clock() - self._t0

    def unaccounted_s(self) -> float:
        """Wall time not yet charged to any bucket — what ``other`` would
        absorb right now.  The stall-abort path charges ``min(idle,
        unaccounted_s())``: the watchdog's idle window can overlap a step
        that completed without beating (the injected-hang seam sits
        between the charge and the beat), and the cap keeps the buckets
        from summing past wall."""
        return max(0.0, self.wall_s() - sum(self._buckets.values()))

    def summary(self) -> dict:
        wall = self.wall_s()
        buckets = dict(self._buckets)
        buckets["other"] = max(0.0, wall - sum(buckets.values()))
        return {
            "wall_s": round(wall, 3),
            "buckets": {k: round(v, 3) for k, v in buckets.items()},
            "steps": self.steps,
            "productive_steps": max(0, self.steps - 1),
        }


def mfu(flops_per_step: float, step_time_s: float, *,
        generation: str = DEFAULT_GENERATION, n_devices: int = 1) -> float:
    """Model FLOPs Utilization of one step against the roofline peak.

    ``flops_per_step`` is the whole-program count (XLA ``cost_analysis``
    convention — the same number ``tune.roofline.score`` consumes), so
    the peak is the full slice's: per-chip bf16 peak × device count.
    Carries roofline's §8 caveat: scan-containing programs undercount,
    making this a LOWER bound on true utilization.
    """
    if step_time_s <= 0 or flops_per_step <= 0 or n_devices <= 0:
        return 0.0
    hw = roofline.get_hardware(generation)
    return flops_per_step / (step_time_s * hw.bf16_flops * n_devices)


def hbm_util(bytes_per_step: float, step_time_s: float, *,
             generation: str = DEFAULT_GENERATION,
             n_devices: int = 1) -> float:
    """HBM-roofline utilization ("bytes-MFU") of one step: the compiled
    program's ``cost_analysis`` bytes accessed over what the slice's HBM
    could stream in that time.  The bandwidth twin of :func:`mfu` — for
    bandwidth-bound programs (the ResNet-50 step, PERF.md §2) THIS is the
    number that says "fast as the hardware allows", and the remat policies
    in :mod:`tpuframe.mem` move it directly.  Same §8 caveat as ``mfu``:
    scan-containing programs undercount bytes, so this is a lower bound.
    """
    if step_time_s <= 0 or bytes_per_step <= 0 or n_devices <= 0:
        return 0.0
    hw = roofline.get_hardware(generation)
    return bytes_per_step / (step_time_s * hw.hbm_bytes_per_s * n_devices)


def flops_fallback(n_params: int, examples_per_step: int,
                   tokens_per_example: int = 1) -> float:
    """Analytic fwd+bwd flops estimate when the compiled program's
    cost_analysis is unavailable: the standard 6·N·D dense heuristic
    (2 flops/param forward + 4 backward, per processed token/example).
    An estimate — cost_analysis wins whenever it exists."""
    return 6.0 * float(n_params) * float(examples_per_step) \
        * float(tokens_per_example)


# ---------------------------------------------------------------------------
# Offline half: the same accounting recomputed from an event stream.
# ---------------------------------------------------------------------------

def _attempts(events: list[dict]) -> list[list[dict]]:
    """Split a merged stream into per-attempt sub-streams (ascending)."""
    by_attempt: dict[int, list[dict]] = {}
    for rec in events:
        by_attempt.setdefault(int(rec.get("attempt", 0)), []).append(rec)
    return [by_attempt[a] for a in sorted(by_attempt)]


def step_times_ms(events: list[dict], *,
                  include_first: bool = False) -> list[float]:
    """Per-step host wall ms from ``step`` events (first step — the
    compile — excluded unless asked; it would dominate any statistic)."""
    steps = [r for r in events if r.get("type") == "step"]
    if not include_first and steps:
        steps = steps[1:]
    return [float(r["wall_ms"]) for r in steps if "wall_ms" in r]


def from_events(events: list[dict], *,
                generation: str | None = None) -> dict:
    """Recompute the goodput breakdown from a (merged) event stream.

    Prefers the writer's own ``run_end`` summary when one exists (the
    live meter saw every boundary); otherwise reconstructs the buckets
    from ``step``/``ckpt_*``/``stall`` events — the crashed-attempt
    path, where no run_end was ever written.  Cross-attempt restart-lost
    time is computed here either way: for each non-final attempt,
    (that attempt's time past its last event) is unknowable, so the
    charge is the *gap* between its last event and the next attempt's
    first, plus any steps the relaunch retrained (visible as step
    indices replayed below the prior attempt's high-water mark).
    """
    out: dict = {"attempts": 0, "restart_lost_s": 0.0,
                 "retrained_steps": 0}
    attempts = _attempts(events)
    out["attempts"] = len(attempts)
    if not attempts:
        out["buckets"] = {b: 0.0 for b in BUCKETS}
        out["wall_s"] = 0.0
        out["steps"] = 0
        return out

    # Cross-attempt stitching.
    for prev, nxt in zip(attempts, attempts[1:]):
        prev_ts = [r["t"] for r in prev if "t" in r]
        nxt_ts = [r["t"] for r in nxt if "t" in r]
        if prev_ts and nxt_ts:
            out["restart_lost_s"] += max(0.0, min(nxt_ts) - max(prev_ts))
        prev_hi = max((int(r["step"]) for r in prev
                       if r.get("type") == "step"), default=0)
        replayed = [int(r["step"]) for r in nxt
                    if r.get("type") == "step" and int(r["step"]) <= prev_hi]
        out["retrained_steps"] += len(replayed)

    # Elastic resizes are attempt-boundary facts like restart-lost time:
    # surface them so ``summarize`` shows which attempts changed world
    # size (retrained_steps is the ≤1-lost-step check's numerator).
    resizes = [r for r in events if r.get("type") == "elastic_resize"]
    if resizes:
        out["elastic_resizes"] = len(resizes)
        out["elastic_transitions"] = [
            f"{int(r.get('n_from', 0))}->{int(r.get('n_to', 0))}"
            for r in resizes]

    # Per-attempt buckets, summed.
    buckets = {b: 0.0 for b in BUCKETS}
    wall = 0.0
    final_step = 0
    n_steps = 0
    mfu_productive = None
    mfu_goodput = None
    hbm_util_productive = None
    peak_hbm = None
    for stream in attempts:
        end = next((r for r in stream if r.get("type") == "run_end"), None)
        if end is not None:
            g = end.get("goodput", {})
            for k, v in g.get("buckets", {}).items():
                if k in buckets:
                    buckets[k] += float(v)
            wall += float(g.get("wall_s", end.get("wall_s", 0.0)))
            final_step = max(final_step, int(end.get("final_step", 0)))
            n_steps += int(g.get("steps") or
                           sum(1 for r in stream if r.get("type") == "step"))
            if end.get("mfu_productive") is not None:
                mfu_productive = float(end["mfu_productive"])
            if end.get("mfu_goodput") is not None:
                mfu_goodput = float(end["mfu_goodput"])
            if end.get("hbm_util_productive") is not None:
                hbm_util_productive = float(end["hbm_util_productive"])
            if end.get("peak_hbm_bytes") is not None:
                peak_hbm = max(peak_hbm or 0,
                               int(end["peak_hbm_bytes"]))
            continue
        # Crashed attempt: rebuild from raw events.  Buckets are
        # accumulated attempt-locally so a later crashed attempt can't
        # clobber an earlier attempt's recorded ``other``.
        ts = [r["t"] for r in stream if "t" in r]
        span = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
        wall += span
        local = {b: 0.0 for b in BUCKETS if b != "other"}
        steps = [r for r in stream if r.get("type") == "step"]
        n_steps += len(steps)
        if steps:
            final_step = max(final_step,
                             max(int(r["step"]) for r in steps))
            local["compile"] += float(steps[0].get("wall_ms", 0.0)) / 1e3
            local["productive"] += sum(
                float(r.get("wall_ms", 0.0)) for r in steps[1:]) / 1e3
            # Schema v2: data-pipeline wait rides on each step record,
            # already excluded from its wall_ms; absent (v1) means zero.
            local["input"] += sum(
                float(r.get("input_wait_ms", 0.0)) for r in steps) / 1e3
        for r in stream:
            if r.get("type") == "ckpt_save":
                # ``block_ms`` (v2) is the slice the step path actually
                # waited — for async saves, just the snapshot; ``ms``
                # spans through commit, which for async runs mostly
                # overlaps training and must not be charged to ckpt.
                blocked = r.get("block_ms")
                if blocked is None:
                    blocked = 0.0 if r.get("async_write") \
                        else r.get("ms", 0.0)
                local["ckpt"] += float(blocked) / 1e3
            elif r.get("type") == "stall":
                local["stall"] += float(r.get("idle_s", 0.0))
        for k, v in local.items():
            buckets[k] += v
        buckets["other"] += max(0.0, span - sum(local.values()))
        for r in stream:
            if r.get("type") == "devmem":
                for dev in r.get("devices", []):
                    b = dev.get("peak_bytes_in_use",
                                dev.get("bytes_in_use"))
                    if b is not None:
                        peak_hbm = max(peak_hbm or 0, int(b))

    out["buckets"] = {k: round(v, 3) for k, v in buckets.items()}
    out["wall_s"] = round(wall, 3)
    out["steps"] = n_steps
    out["final_step"] = final_step
    if mfu_productive is not None:
        out["mfu_productive"] = mfu_productive
    if mfu_goodput is not None:
        out["mfu_goodput"] = mfu_goodput
    if hbm_util_productive is not None:
        out["hbm_util_productive"] = hbm_util_productive
    if peak_hbm is not None:
        out["peak_hbm_bytes"] = peak_hbm

    # Recompute MFU / HBM utilization offline when the manifest recorded
    # the cost models (run_start carries flops_per_step/bytes_per_step) —
    # lets ``summarize`` work on crashed logs that never wrote run_end.
    if mfu_productive is None or hbm_util_productive is None:
        start = next((r for r in events if r.get("type") == "run_start"),
                     None)
        times = step_times_ms(events)
        if start and times:
            gen = (generation or start.get("generation")
                   or DEFAULT_GENERATION)
            mean_s = sum(times) / len(times) / 1e3
            n_dev = int(start.get("devices", 1))
            if mfu_productive is None and start.get("flops_per_step"):
                out["mfu_productive"] = mfu(
                    float(start["flops_per_step"]), mean_s,
                    generation=gen, n_devices=n_dev)
            if hbm_util_productive is None and start.get("bytes_per_step"):
                out["hbm_util_productive"] = hbm_util(
                    float(start["bytes_per_step"]), mean_s,
                    generation=gen, n_devices=n_dev)
    return out


# ---------------------------------------------------------------------------
# Anomaly detection — the "what went wrong" half of the analyzer.
# ---------------------------------------------------------------------------

def find_anomalies(events: list[dict], *, slow_factor: float = 3.0,
                   window: int = 16, retry_storm: int = 5,
                   retry_window_s: float = 60.0,
                   mfu_min: float | None = None,
                   blocked_ms: float = 1000.0) -> list[dict]:
    """Flag suspicious shapes in a merged event stream.

    Detectors (each finding: ``{"kind", "detail", ...anchors}``):

      step_regression — a step's wall ms exceeds ``slow_factor`` × the
        rolling median of the previous ``window`` steps (first/compile
        step excluded).  The rolling median, not the global one: a run
        that *gradually* slows (fragmenting HBM, growing host GC) trips
        the detector where a global median would absorb it.
      stall            — every heartbeat ``stall`` event.
      retry_storm      — ≥ ``retry_storm`` retry events inside any
        ``retry_window_s`` window: a flaky backend being hammered.
      low_mfu          — reported MFU below ``mfu_min`` (off by default;
        thresholds are workload policy, not a universal constant).
      no_run_end       — an attempt that never wrote ``run_end``: the
        run died (crash, preemption without commit, or still live).
      blocked_input    — a step waited > ``blocked_ms`` on the data
        pipeline (``input_wait_ms``): the loader can't keep up, the
        exact stall class arXiv:1909.09756 warns erases pod efficiency.
      blocked_ckpt     — a save blocked the step path > ``blocked_ms``
        (``block_ms``; sync saves' full ``ms``): checkpointing is on
        the step path — the async pipeline exists to make this ~0.
      goodput_invariant — an attempt's ``run_end`` buckets do not sum
        to its wall time.  Flagged loudly instead of renormalized: a
        violated partition means a double-charged or lost slice, and
        silently rescaling it would hide the accounting bug the
        invariant exists to catch.
      leaked_span / orphan_span — tracing-plane failure modes
        (``obs.tracing.span_anomalies``): a span opened with no close
        before the stream ended (a request a replica never answered, or
        a process that died holding it), and a close/child/note whose
        span or parent was never opened (a propagation bug or torn
        context).
    """
    findings: list[dict] = []

    steps = [r for r in events if r.get("type") == "step"
             and "wall_ms" in r]
    recent: list[float] = []
    for r in steps[1:]:
        ms = float(r["wall_ms"])
        if len(recent) >= 3:
            med = sorted(recent)[len(recent) // 2]
            if med > 0 and ms > slow_factor * med:
                findings.append({
                    "kind": "step_regression", "step": int(r["step"]),
                    "wall_ms": round(ms, 2),
                    "rolling_median_ms": round(med, 2),
                    "detail": f"step {r['step']} took {ms:.1f} ms — "
                              f"{ms / med:.1f}x the rolling median "
                              f"({med:.1f} ms)"})
        recent.append(ms)
        if len(recent) > window:
            recent.pop(0)

    for r in events:
        if r.get("type") == "stall":
            findings.append({
                "kind": "stall", "last_step": r.get("last_step"),
                "idle_s": r.get("idle_s"),
                "detail": f"heartbeat stall: no step for "
                          f"{r.get('idle_s', '?')}s after step "
                          f"{r.get('last_step', '?')}"})

    retries = sorted(float(r["t"]) for r in events
                     if r.get("type") == "retry" and "t" in r)
    lo = 0
    reported_storm = False
    for hi in range(len(retries)):
        while retries[hi] - retries[lo] > retry_window_s:
            lo += 1
        if hi - lo + 1 >= retry_storm and not reported_storm:
            findings.append({
                "kind": "retry_storm", "count": hi - lo + 1,
                "window_s": retry_window_s,
                "detail": f"{hi - lo + 1} I/O retries within "
                          f"{retry_window_s:.0f}s — storage backend "
                          f"degraded"})
            reported_storm = True  # one report per stream, not per pair

    if mfu_min is not None:
        summary = from_events(events)
        got = summary.get("mfu_productive")
        if got is not None and got < mfu_min:
            findings.append({
                "kind": "low_mfu", "mfu": round(got, 4),
                "threshold": mfu_min,
                "detail": f"MFU {got:.2%} below threshold "
                          f"{mfu_min:.2%}"})

    if blocked_ms is not None:
        for r in events:
            if (r.get("type") == "step"
                    and float(r.get("input_wait_ms") or 0.0) > blocked_ms):
                w = float(r["input_wait_ms"])
                findings.append({
                    "kind": "blocked_input", "step": r.get("step"),
                    "input_wait_ms": round(w, 2), "threshold_ms": blocked_ms,
                    "detail": f"step {r.get('step')} blocked {w:.0f} ms on "
                              f"the input pipeline (> {blocked_ms:.0f} ms)"})
            elif r.get("type") == "ckpt_save":
                blk = r.get("block_ms")
                if blk is None and not r.get("async_write"):
                    blk = r.get("ms")  # schema v1 sync save: all blocking
                if blk is not None and float(blk) > blocked_ms:
                    findings.append({
                        "kind": "blocked_ckpt", "step": r.get("step"),
                        "block_ms": round(float(blk), 2),
                        "threshold_ms": blocked_ms,
                        "detail": f"save at step {r.get('step')} blocked "
                                  f"the step path {float(blk):.0f} ms "
                                  f"(> {blocked_ms:.0f} ms)"})

    for stream in _attempts(events):
        if not any(r.get("type") == "run_end" for r in stream):
            att = stream[0].get("attempt", 0) if stream else 0
            last = max((int(r["step"]) for r in stream
                        if r.get("type") == "step"), default=None)
            findings.append({
                "kind": "no_run_end", "attempt": att, "last_step": last,
                "detail": f"attempt {att} never wrote run_end (died or "
                          f"still running); last seen step: {last}"})
            continue
        for end in (r for r in stream if r.get("type") == "run_end"):
            g = end.get("goodput", {})
            wall = float(g.get("wall_s", end.get("wall_s", 0.0)))
            total = sum(float(v) for v in g.get("buckets", {}).values())
            # The meter's ``other`` bucket absorbs the remainder, so the
            # partition is exact up to per-bucket rounding (3 decimals,
            # ≤ 0.5 ms each) — anything past that slack is a real
            # double-charge or lost slice, never noise.
            tol = max(0.05, 0.001 * len(g.get("buckets", {})) + 0.01)
            if g.get("buckets") and abs(total - wall) > tol:
                att = end.get("attempt", 0)
                findings.append({
                    "kind": "goodput_invariant", "attempt": att,
                    "wall_s": round(wall, 3), "bucket_sum_s": round(total, 3),
                    "detail": f"attempt {att} goodput buckets sum to "
                              f"{total:.3f}s but wall is {wall:.3f}s — "
                              f"bucket accounting violated (delta "
                              f"{total - wall:+.3f}s)"})

    if any(r.get("type") in ("span_open", "span_close", "span_note")
           for r in events):
        # Lazy on purpose: training-only logs never pay the import.
        from tpuframe.obs import tracing

        findings.extend(tracing.span_anomalies(events))
    return findings


# ---------------------------------------------------------------------------
# Serving latency accounting (tpuframe.serve's serve_* events).
# ---------------------------------------------------------------------------

def _pct(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return float(sorted_vals[idx])


def serve_stats(events: list) -> dict | None:
    """TTFT/TPOT percentiles and token throughput from ``serve_*``
    events; None when the log carries no serving traffic (so training
    summaries stay serving-free).  TTFT = arrival to first token (the
    prefill + queueing number); TPOT = per-token decode cadence after
    the first.  tokens/sec/chip divides by the ``serve_summary`` device
    count — the serving analogue of MFU's per-chip normalization."""
    reqs = [r for r in events if r.get("type") == "serve_request"]
    steps = [r for r in events if r.get("type") == "serve_step"]
    summary = next((r for r in reversed(events)
                    if r.get("type") == "serve_summary"), None)
    if not (reqs or steps or summary is not None):
        return None

    ttft = sorted(float(r["ttft_ms"]) for r in reqs
                  if r.get("ttft_ms") is not None)
    tpot = sorted(float(r["tpot_ms"]) for r in reqs
                  if r.get("tpot_ms") is not None)

    tokens_per_s = None
    n_devices = 1
    if summary is not None:
        n_devices = max(1, int(summary.get("n_devices") or 1))
        if summary.get("tokens_per_s") is not None:
            tokens_per_s = float(summary["tokens_per_s"])
    if tokens_per_s is None and steps:
        # No summary (run died mid-serve): reconstruct from the steps.
        toks = sum(int(r.get("produced") or 0) + int(r.get("admitted") or 0)
                   for r in steps)
        wall_s = sum(float(r.get("wall_ms") or 0.0) for r in steps) / 1e3
        tokens_per_s = toks / wall_s if wall_s > 0 else None

    return {
        "requests": len(reqs),
        "steps": len(steps),
        "output_tokens": sum(int(r.get("output_tokens") or 0)
                             for r in reqs),
        "ttft_ms": {q: round(_pct(ttft, v), 3) for q, v in
                    (("p50", 0.5), ("p90", 0.9), ("p99", 0.99))}
        if ttft else None,
        "tpot_ms": {q: round(_pct(tpot, v), 3) for q, v in
                    (("p50", 0.5), ("p90", 0.9), ("p99", 0.99))}
        if tpot else None,
        "tokens_per_s": round(tokens_per_s, 2)
        if tokens_per_s is not None else None,
        "tokens_per_s_per_chip": round(tokens_per_s / n_devices, 2)
        if tokens_per_s is not None else None,
        "n_devices": n_devices,
    }


def fleet_stats(events: list) -> dict | None:
    """Router-level rollup of the fleet's ``router_*`` events; None when
    the log carries no router traffic.  ``lost`` is the fleet contract's
    headline number — admitted minus retired, which a healthy run keeps
    at zero through drain/redispatch — and shed is reported beside it
    because an explicitly shed request is *not* a lost one (it was never
    acknowledged)."""
    done = [r for r in events if r.get("type") == "router_request"]
    admits = sum(1 for r in events if r.get("type") == "router_admit")
    sheds = sum(1 for r in events if r.get("type") == "router_shed")
    drains = [r for r in events if r.get("type") == "router_drain"]
    hedges = sum(1 for r in events if r.get("type") == "router_hedge")
    redispatches = sum(1 for r in events
                       if r.get("type") == "router_redispatch")
    summary = next((r for r in reversed(events)
                    if r.get("type") == "router_summary"), None)
    if not (done or admits or sheds or summary is not None):
        return None

    with_ttft = sorted((r for r in done if r.get("ttft_ms") is not None),
                       key=lambda r: float(r["ttft_ms"]))
    ttft = [float(r["ttft_ms"]) for r in with_ttft]
    # Exemplars: each percentile row links the ACTUAL request at that
    # rank — its trace id (when traced) and rid — so "p99 regressed"
    # becomes "open this trace's waterfall", not a number with no story.
    exemplars = None
    if with_ttft:
        exemplars = {}
        for q, frac in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
            idx = min(len(with_ttft) - 1,
                      int(round(frac * (len(with_ttft) - 1))))
            rec = with_ttft[idx]
            exemplars[q] = {"id": rec.get("id"),
                            "trace": rec.get("trace"),
                            "ttft_ms": round(float(rec["ttft_ms"]), 3)}
    by_replica: dict = {}
    for r in done:
        name = str(r.get("replica"))
        by_replica[name] = by_replica.get(name, 0) + 1

    # Live-rollout accounting (PR 17): final per-replica weights version
    # and the mixed-version window — first replica on the new version to
    # last replica on it (the boundedness the rollout controller
    # proves).  None when the log carries no rollout traffic.
    versions = None
    ro_steps = [r for r in events if r.get("type") == "rollout_step"]
    ro_done = next((r for r in reversed(events)
                    if r.get("type") == "rollout_done"), None)
    ro_abort = next((r for r in reversed(events)
                     if r.get("type") == "rollout_abort"), None)
    if ro_steps or ro_done or ro_abort:
        by_rep_version: dict = {}
        swap_ts = []
        for r in ro_steps:
            phase = r.get("phase")
            if phase in ("swapped", "relaunched"):
                by_rep_version[str(r.get("replica"))] = r.get("version")
                if r.get("t") is not None:
                    swap_ts.append(float(r["t"]))
            elif phase == "rolled_back":
                by_rep_version[str(r.get("replica"))] = r.get("version")
        versions = {
            "by_replica": dict(sorted(by_rep_version.items())),
            "target": (ro_done or ro_abort or {}).get("version"),
            "mixed_window_s": round(max(swap_ts) - min(swap_ts), 3)
            if len(swap_ts) >= 2 else 0.0,
            "aborted": ro_abort is not None,
            "abort_metric": ro_abort.get("metric") if ro_abort else None,
        }
    return {
        "requests": len(done),
        "admitted": admits,
        "shed": sheds,
        "lost": admits - len(done),
        "hedged": hedges,
        "redispatched": redispatches,
        "drains": [{"replica": r.get("replica"),
                    "reason": r.get("reason")} for r in drains],
        "by_replica": dict(sorted(by_replica.items())),
        "versions": versions,
        "ttft_ms": {q: round(_pct(ttft, v), 3) for q, v in
                    (("p50", 0.5), ("p90", 0.9), ("p99", 0.99))}
        if ttft else None,
        "ttft_exemplars": exemplars,
    }


# ---------------------------------------------------------------------------
# Run comparison — the regression sentry (``python -m tpuframe.obs compare``).
# ---------------------------------------------------------------------------

# Thresholds are in the units of the metric they guard: percentage
# increase for latencies (a run B more than ``step_pct``% slower at p50
# or p90 regressed), absolute fraction for the productive share of wall,
# relative fraction for MFU.  Policy defaults, overridable per-call and
# per-CLI-flag — a latency-critical serving fleet will want tighter ones.
DEFAULT_COMPARE_THRESHOLDS = {
    "step_pct": 25.0,        # step-time p50/p90 increase (%)
    "productive_drop": 0.10,  # absolute drop in productive wall fraction
    "mfu_drop": 0.10,        # relative mfu_productive drop (fraction)
    "serve_pct": 25.0,       # serve TTFT/TPOT p90 increase (%)
}


def _compare_metrics(events: list[dict], *,
                     generation: str | None = None) -> dict:
    """The comparable facts of one merged stream, in one flat dict."""
    out: dict = {}
    times = sorted(step_times_ms(events))
    if times:
        out["step_p50_ms"] = _pct(times, 0.5)
        out["step_p90_ms"] = _pct(times, 0.9)
    summary = from_events(events, generation=generation)
    wall = summary.get("wall_s") or 0.0
    if wall > 0:
        out["productive_frac"] = \
            summary["buckets"].get("productive", 0.0) / wall
    if summary.get("mfu_productive") is not None:
        out["mfu_productive"] = summary["mfu_productive"]
    serve = serve_stats(events)
    if serve is not None:
        if serve.get("ttft_ms"):
            out["serve_ttft_p90_ms"] = serve["ttft_ms"]["p90"]
        if serve.get("tpot_ms"):
            out["serve_tpot_p90_ms"] = serve["tpot_ms"]["p90"]
    fleet = fleet_stats(events)
    if fleet is not None and fleet.get("ttft_ms"):
        # End-to-end (router queue wait + replica TTFT): the number the
        # chaos proof bounds at <=2x baseline under a replica kill.
        out["router_ttft_p90_ms"] = fleet["ttft_ms"]["p90"]
    return out


def compare_runs(a_events: list[dict], b_events: list[dict], *,
                 thresholds: dict | None = None,
                 generation: str | None = None) -> dict:
    """Diff run B against baseline A on goodput, step time, MFU and serve
    percentiles.  Returns ``{"metrics": {name: {"a", "b", ...}},
    "regressions": [...], "improvements": [...]}`` — a metric only
    participates when BOTH runs carry it (a training-only baseline never
    "regresses" against a run that added serving traffic)."""
    th = dict(DEFAULT_COMPARE_THRESHOLDS)
    th.update(thresholds or {})
    ma = _compare_metrics(a_events, generation=generation)
    mb = _compare_metrics(b_events, generation=generation)

    # (metric, kind, threshold): ``pct_increase`` flags B > A by more
    # than threshold %; ``abs_drop``/``rel_drop`` flag B < A by more than
    # an absolute / relative amount (higher-is-better metrics).
    checks = (
        ("step_p50_ms", "pct_increase", th["step_pct"]),
        ("step_p90_ms", "pct_increase", th["step_pct"]),
        ("productive_frac", "abs_drop", th["productive_drop"]),
        ("mfu_productive", "rel_drop", th["mfu_drop"]),
        ("serve_ttft_p90_ms", "pct_increase", th["serve_pct"]),
        ("serve_tpot_p90_ms", "pct_increase", th["serve_pct"]),
        ("router_ttft_p90_ms", "pct_increase", th["serve_pct"]),
    )
    out: dict = {"metrics": {}, "regressions": [], "improvements": []}
    for name, kind, threshold in checks:
        a, b = ma.get(name), mb.get(name)
        if a is None or b is None:
            continue
        entry = {"metric": name, "a": round(float(a), 4),
                 "b": round(float(b), 4), "threshold": threshold}
        out["metrics"][name] = entry
        if kind == "pct_increase":
            if a <= 0:
                continue
            delta_pct = 100.0 * (b - a) / a
            entry["delta_pct"] = round(delta_pct, 2)
            if delta_pct > threshold:
                entry["detail"] = (f"{name}: {a:.2f} -> {b:.2f} "
                                   f"(+{delta_pct:.1f}% > {threshold:.0f}%)")
                out["regressions"].append(entry)
            elif delta_pct < -threshold:
                out["improvements"].append(entry)
        elif kind == "abs_drop":
            entry["delta"] = round(float(b - a), 4)
            if a - b > threshold:
                entry["detail"] = (f"{name}: {a:.3f} -> {b:.3f} "
                                   f"(dropped {a - b:.3f} > {threshold})")
                out["regressions"].append(entry)
            elif b - a > threshold:
                out["improvements"].append(entry)
        else:  # rel_drop
            if a <= 0:
                continue
            rel = (a - b) / a
            entry["delta_rel"] = round(rel, 4)
            if rel > threshold:
                entry["detail"] = (f"{name}: {a:.4f} -> {b:.4f} "
                                   f"(-{100 * rel:.1f}% > "
                                   f"{100 * threshold:.0f}%)")
                out["regressions"].append(entry)
            elif rel < -threshold:
                out["improvements"].append(entry)
    return out
