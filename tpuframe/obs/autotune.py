"""Autotune — the runnable equivalent of Horovod's Bayesian knob tuner.

Horovod autotunes ``HOROVOD_FUSION_THRESHOLD`` / ``HOROVOD_CYCLE_TIME`` at
runtime inside its C++ coordinator (SURVEY.md §3b, optional row).  Under
XLA the tunable surface is compile-time env knobs, and because every trial
is a fresh compiled program, the right tool is an out-of-process sweep:
run the benchmark once per candidate setting, keep what measures fastest.

This module implements greedy coordinate descent over declared knob axes —
measure a baseline, then sweep one axis at a time keeping the best value
found so far (the same one-factor-at-a-time structure Horovod's tuner
reduces to for independent knobs, minus the Bayesian prior; with ~4 values
per axis the full greedy pass is ~a dozen trials and needs no prior).

Library use (any measure function) and CLI:

    python -m tpuframe.obs.autotune --out report.json \
        --axis TPUFRAME_BENCH_BATCH=128,256,512,1024 \
        --axis TPUFRAME_FUSION_THRESHOLD=,0,8388608,67108864 \
        -- python bench.py

The command must print one JSON line with a ``value`` field (bench.py's
contract); higher is better.  The report records every trial, the winning
env, and the winning value; ``--apply`` re-echoes the winning env as shell
exports.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable

Measure = Callable[[dict], float]  # env overrides -> metric (higher better)


@dataclass
class Axis:
    """One tunable knob: env var name + candidate values ('' = unset)."""

    name: str
    values: list[str]

    @classmethod
    def parse(cls, spec: str) -> "Axis":
        if "=" not in spec:
            raise ValueError(f"axis spec {spec!r} is not NAME=v1,v2,...")
        name, vals = spec.split("=", 1)
        return cls(name=name, values=vals.split(","))


@dataclass
class Report:
    trials: list[dict] = field(default_factory=list)
    best_env: dict = field(default_factory=dict)
    best_value: float = float("-inf")

    def as_dict(self) -> dict:
        # None when every trial failed: -inf would serialize as the
        # non-standard -Infinity and break strict JSON consumers.
        best = (None if self.best_value == float("-inf")
                else self.best_value)
        return {"trials": self.trials, "best_env": self.best_env,
                "best_value": best}


def autotune(measure: Measure, axes: list[Axis], *,
             budget: int | None = None, log=None) -> Report:
    """Greedy coordinate descent: baseline with every axis at its first
    value, then per axis try the remaining values, keeping the argmax.
    ``budget`` caps total measurements; ``measure`` exceptions record the
    trial as failed (value -inf) and the sweep continues."""
    report = Report()
    env = {a.name: a.values[0] for a in axes}
    spent = 0

    def run(env_now: dict) -> float:
        nonlocal spent
        if budget is not None and spent >= budget:
            raise _BudgetExhausted
        spent += 1
        t0 = time.time()
        try:
            value = float(measure(dict(env_now)))
            err = None
        except _BudgetExhausted:
            raise
        except Exception as e:  # noqa: BLE001 — a failed trial is data
            value, err = float("-inf"), f"{type(e).__name__}: {e}"[:200]
        # None (JSON null) for failed trials: float('-inf') would make
        # the report file invalid JSON (-Infinity).
        trial = {"env": dict(env_now),
                 "value": None if err else value,
                 "seconds": round(time.time() - t0, 1)}
        if err:
            trial["error"] = err
        report.trials.append(trial)
        if log:
            log(f"trial {env_now} -> {value}"
                + (f" ({err})" if err else ""))
        if value > report.best_value:
            report.best_value = value
            report.best_env = dict(env_now)
        return value

    try:
        best = run(env)
        for axis in axes:
            best_val = env[axis.name]
            for v in axis.values[1:]:
                candidate = dict(env, **{axis.name: v})
                got = run(candidate)
                if got > best:
                    best, best_val = got, v
            env[axis.name] = best_val  # greedy: keep the winner, move on
    except _BudgetExhausted:
        if log:
            log(f"budget {budget} exhausted after {spent} trials")
    return report


class _BudgetExhausted(Exception):
    pass


def subprocess_measure(argv: list[str], *, timeout: float = 1800) -> Measure:
    """A Measure that runs ``argv`` with env overrides applied ('' =
    remove) and parses the last stdout line that is a JSON object with a
    ``value`` field — bench.py's output contract."""

    def measure(overrides: dict) -> float:
        env = dict(os.environ)
        for k, v in overrides.items():
            if v == "":
                env.pop(k, None)
            else:
                env[k] = v
        proc = subprocess.run(argv, env=env, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(f"rc={proc.returncode}: "
                               f"{proc.stderr[-300:]}")
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "value" in obj:
                value = float(obj["value"])
                if not math.isfinite(value):
                    # json.loads accepts NaN/Infinity; recording them would
                    # re-break the strict-JSON report this module guards.
                    raise RuntimeError(f"non-finite benchmark value {value}")
                return value
        raise RuntimeError("no JSON line with a 'value' field on stdout")

    return measure


def replay_offline_topk(measure: Measure, *, program: str | None = None,
                        family: str | None = None,
                        generation: str | None = None, k: int = 3,
                        db=None, save: bool = True, log=None) -> Report:
    """Bridge from the offline autotuner (tpuframe.tune): when a chip
    window opens, replay the offline-RANKED top-k candidates through the
    real measured loop and upgrade their tuning-DB records from predicted
    to measured.

    The offline sweep's roofline ranking is a compiler-derived lower
    bound (and blind inside pallas custom calls, PERF.md §8) — this is
    the step that turns it into ground truth.  Every candidate that
    measures successfully is upgraded, not just the winner: a measured
    loser is exactly as valuable to the DB's ranking as a measured
    winner.  ``measure`` follows this module's contract (env-override
    dict -> metric, higher is better) — e.g. ``subprocess_measure`` over
    bench.py on the bench chip.
    """
    from tpuframe.tune import db as tune_db

    if db is None:
        db = tune_db.TuningDB.open()
    candidates = db.top_k(k, program=program, family=family,
                          generation=generation)
    if log:
        log(f"replaying offline top-{len(candidates)} "
            f"(program={program}, family={family}, gen={generation})")
    report = Report()
    for rec in candidates:
        overrides = rec.env_overrides()
        t0 = time.time()
        try:
            value = float(measure(dict(overrides)))
            err = None
        except Exception as e:  # noqa: BLE001 — a failed trial is data
            value, err = float("-inf"), f"{type(e).__name__}: {e}"[:200]
        trial = {"env": dict(overrides),
                 "value": None if err else value,
                 "seconds": round(time.time() - t0, 1),
                 "config": dict(rec.config)}
        if err:
            trial["error"] = err
        report.trials.append(trial)
        if log:
            log(f"trial {rec.config} -> {value}"
                + (f" ({err})" if err else ""))
        if err is None:
            db.upgrade_measured(rec, value, unit="value", maximize=True)
        if value > report.best_value:
            report.best_value = value
            report.best_env = dict(overrides)
    if save and any(t["value"] is not None for t in report.trials):
        db.save()
    return report


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="greedy env-knob autotune over a benchmark command")
    ap.add_argument("--axis", action="append", default=[],
                    help="NAME=v1,v2,... (repeatable; '' value = unset)")
    ap.add_argument("--out", default="autotune_report.json")
    ap.add_argument("--budget", type=int, default=None)
    ap.add_argument("--timeout", type=float, default=1800)
    ap.add_argument("--apply", action="store_true",
                    help="print the winning env as shell exports")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- benchmark command (prints a JSON 'value' line)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("no benchmark command given (after --)")
    if not args.axis:
        ap.error("at least one --axis required")

    axes = [Axis.parse(s) for s in args.axis]
    log = lambda m: print(f"[autotune] {m}", file=sys.stderr, flush=True)  # noqa: E731
    report = autotune(subprocess_measure(cmd, timeout=args.timeout), axes,
                      budget=args.budget, log=log)
    with open(args.out, "w") as f:
        json.dump(report.as_dict(), f, indent=1)
    log(f"best {report.best_value} with {report.best_env}; "
        f"report -> {args.out}")
    if report.best_value == float("-inf"):
        log("every trial failed — exiting nonzero")
        return 1
    if args.apply:
        for k, v in report.best_env.items():
            print(f"export {k}={v!r}" if v else f"unset {k}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
