"""Per-request tracing plane — spans over the structured event log.

The fleet can report *that* p99 TTFT moved (PERF §22/§25) but not *why*:
``router_*`` and ``serve_*`` events carry ids that only join by luck, so
no tool can decompose a slow request into queue wait, dispatch, prefill
and decode time, or follow it through a hedge race or a drain
re-dispatch.  This module is the missing correlation layer — the
Horovod-timeline lesson (arXiv:1802.05799) applied to serving: aggregate
numbers cannot localize a straggler; a per-operation timeline can.

Span model (see DESIGN.md "Request tracing & SLOs"):

  - A *trace* is one request's end-to-end story.  ``Router.submit``
    mints the trace id at admission (``mint(rid)``, sampled by
    ``TPUFRAME_TRACE_SAMPLE``); the id rides the dispatch payload into
    the replica (``/generate`` body keys ``trace``/``span``) so every
    process annotates the same trace without a shared clock or a
    central collector.
  - A *span* is one timed phase: ``request`` (root, router),
    ``attempt`` (one dispatch — first placement, hedge or redispatch),
    ``serve`` (replica-side lifetime), ``queue``/``prefill``/``decode``
    (scheduler phases).  Spans carry ``parent`` links; hedge losers
    close with ``duplicate=true`` under the same trace.
  - Spans are ordinary typed events (``span_open``/``span_close``/
    ``span_note``) through :mod:`tpuframe.obs.events` — the flight
    recorder, the multi-host merge and the schema validator get them
    for free, and a crash tears at a line boundary like every other
    event.

Clock contract: every ``ms`` on a ``span_close`` is a *same-process
monotonic* delta (router and scheduler both run on ``time.monotonic``
since the satellite-6 reconciliation) — cross-process subtraction never
happens.  The wall-clock envelope ``t`` orders spans for display only.
Consequence: for a completed request,

    root ttft_ms == wait_ms + queue.ms + prefill.ms   (± rounding)

which ``verify_traces`` enforces within ``tol_ms`` — the accounting
invariant that makes "where did the TTFT go" answerable.

This module is the ONE sanctioned emitter of span event types (lint
TF123): everything else calls ``open_span``/``close_span``/``span``/
``note`` so parent links, the open-span registry (the leak gauge on
``/metrics``) and the sampling decision cannot be half-applied.

Offline half: ``build_traces`` reconstructs span trees from a merged
stream, ``verify_traces`` makes orphan/leaked/unclosed spans and
phase-sum violations loud, ``critical_path`` walks the chain of spans
that gated completion.  ``python -m tpuframe.obs trace`` renders the
waterfalls; ``check()`` is the CI-gate leg (seeded positives included —
the gate refuses to run blind).

Pure stdlib, no jax import — same contract as ``obs.events``.
"""

from __future__ import annotations

import os
import threading
import zlib
from dataclasses import dataclass, field

from tpuframe.obs import events as obs_events

ENV_SAMPLE = "TPUFRAME_TRACE_SAMPLE"

SPAN_EVENT_TYPES = ("span_open", "span_close", "span_note")

# The per-type required fields this plane relies on, pinned here AND in
# obs/events.py REQUIRED_FIELDS; check() cross-checks the two so a
# schema edit that strands shipped traces fails the gate.
SPAN_REQUIRED_FIELDS = {
    "span_open": ("trace", "span", "name"),
    "span_close": ("trace", "span", "ms"),
    "span_note": ("trace", "note"),
}

_ids_lock = threading.Lock()
_next_id = 0

# In-process registry of spans opened but not yet closed — the live
# "leak" signal: the exporter renders its size as the label-free
# ``tpuframe_open_spans`` gauge, so a replica accumulating unclosed
# spans is visible on /metrics before any offline analysis runs.
_open_lock = threading.Lock()
_open: dict[tuple, str] = {}      # (trace, span) -> name


def resolve_sample() -> float:
    """The ``TPUFRAME_TRACE_SAMPLE`` fraction, clamped to [0, 1].
    Default 1.0 — every request traced; production fleets dial down."""
    raw = os.environ.get(ENV_SAMPLE, "").strip()
    if not raw:
        return 1.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 1.0


def sampled(key) -> bool:
    """Deterministic sampling decision for ``key`` (a rid or a string
    tag) against the resolved fraction.  Arithmetic hash, NOT ``hash()``
    — the decision must agree across processes and runs regardless of
    ``PYTHONHASHSEED``."""
    frac = resolve_sample()
    if frac >= 1.0:
        return True
    if frac <= 0.0:
        return False
    if isinstance(key, int):
        h = (key * 2654435761) & 0xFFFFFFFF
    else:
        h = zlib.crc32(str(key).encode())
    return h / 2.0 ** 32 < frac


def mint(key, *, force: bool = False) -> str | None:
    """Mint a trace id for ``key`` or return None when sampled out.
    ``force=True`` skips sampling (fleet-operation traces like a rollout
    are one-per-event, never volume).  The pid suffix keeps ids unique
    when a relaunched router reuses rids in the same events dir."""
    if not force and not sampled(key):
        return None
    return f"t{key}.{os.getpid() & 0xFFFF:04x}"


def _new_span_id() -> str:
    global _next_id
    with _ids_lock:
        _next_id += 1
        n = _next_id
    return f"s{os.getpid() & 0xFFFF:04x}.{n:x}"


def open_span(trace: str, name: str, *, parent: str | None = None,
              **fields) -> str:
    """Open a span under ``trace`` and return its span id.  Best-effort
    like every emit: with events off this still mints the id and tracks
    the open span (the gauge stays live), it just writes nothing."""
    span = _new_span_id()
    with _open_lock:
        _open[(trace, span)] = name
    obs_events.emit("span_open", trace=trace, span=span, name=name,
                    parent=parent, **fields)
    return span


def close_span(trace: str, span: str, ms, **fields) -> None:
    with _open_lock:
        _open.pop((trace, span), None)
    obs_events.emit("span_close", trace=trace, span=span,
                    ms=round(float(ms), 3), **fields)


def span(trace: str, name: str, *, parent: str | None = None,
         ms=0.0, **fields) -> str:
    """An already-measured phase as an atomic open+close pair — the
    scheduler's queue/prefill/decode spans, whose boundaries are clock
    reads it already takes."""
    sid = _new_span_id()
    obs_events.emit("span_open", trace=trace, span=sid, name=name,
                    parent=parent)
    obs_events.emit("span_close", trace=trace, span=sid,
                    ms=round(float(ms), 3), **fields)
    return sid


def note(trace: str, text: str, *, span: str | None = None,
         **fields) -> None:
    """Annotate a trace (optionally anchored to a span): drain
    re-queues, rollout swaps — the sibling events that explain why a
    waterfall has a gap without being timed phases themselves."""
    obs_events.emit("span_note", trace=trace, note=text, span=span,
                    **fields)


def open_span_count() -> int:
    with _open_lock:
        return len(_open)


def open_spans() -> list[tuple[str, str, str]]:
    """Snapshot of (trace, span, name) still open in this process."""
    with _open_lock:
        return [(t, s, n) for (t, s), n in sorted(_open.items())]


# ---------------------------------------------------------------------------
# Reconstruction — the offline half (CLI, tests, CI selfcheck).
# ---------------------------------------------------------------------------

@dataclass
class Span:
    """One reconstructed span: its open/close records and children."""

    trace: str
    span: str
    name: str | None = None
    parent: str | None = None
    opened: dict | None = None
    closed: dict | None = None
    notes: list = field(default_factory=list)
    children: list = field(default_factory=list)

    @property
    def complete(self) -> bool:
        return self.opened is not None and self.closed is not None

    @property
    def ms(self) -> float | None:
        if self.closed is None:
            return None
        return float(self.closed.get("ms") or 0.0)

    def end_t(self) -> float | None:
        """Wall-clock end estimate (open ``t`` + duration) — display and
        critical-path ordering only, never duration arithmetic."""
        if self.opened is None or self.ms is None:
            return None
        return float(self.opened.get("t") or 0.0) + self.ms / 1e3


@dataclass
class Trace:
    """One trace's span tree plus its unanchored notes."""

    trace: str
    spans: dict = field(default_factory=dict)    # span id -> Span
    roots: list = field(default_factory=list)
    notes: list = field(default_factory=list)

    def complete_roots(self, name: str = "request") -> list:
        return [sp for sp in self.roots
                if sp.name == name and sp.complete]


def build_traces(events: list) -> dict:
    """Reconstruct ``{trace_id: Trace}`` from a merged event stream.
    Tolerant by design — a torn stream still yields a tree; the
    judgments (orphans, leaks, sum violations) live in
    ``span_anomalies``/``verify_traces``."""
    traces: dict[str, Trace] = {}
    for r in events:
        etype = r.get("type")
        if etype not in SPAN_EVENT_TYPES:
            continue
        tid = str(r.get("trace"))
        tv = traces.setdefault(tid, Trace(trace=tid))
        sid = r.get("span")
        if etype == "span_note":
            if sid is not None and sid in tv.spans:
                tv.spans[sid].notes.append(r)
            tv.notes.append(r)
            continue
        sid = str(sid)
        sp = tv.spans.setdefault(sid, Span(trace=tid, span=sid))
        if etype == "span_open":
            if sp.opened is None:
                sp.opened = r
                sp.name = r.get("name")
                sp.parent = r.get("parent")
        else:
            if sp.closed is None:
                sp.closed = r
    for tv in traces.values():
        for sp in tv.spans.values():
            if sp.opened is None:
                continue
            if sp.parent is None:
                tv.roots.append(sp)
            elif sp.parent in tv.spans:
                tv.spans[sp.parent].children.append(sp)
    return traces


def span_anomalies(events: list) -> list[dict]:
    """Leaked (opened, never closed) and orphan (close/note/child with
    no opened parent) spans — the loud failure modes of a propagation
    bug or a torn process.  Each finding: ``{"kind", "detail", ...}``,
    the ``find_anomalies`` contract."""
    out: list[dict] = []
    traces = build_traces(events)
    for tid, tv in sorted(traces.items()):
        for sid, sp in sorted(tv.spans.items()):
            if sp.opened is None:
                host = (sp.closed or {}).get("host")
                out.append({
                    "kind": "orphan_span", "trace": tid, "span": sid,
                    "host": host,
                    "detail": f"trace {tid}: span_close for {sid} with "
                              f"no span_open (host {host})"})
                continue
            host = sp.opened.get("host")
            if sp.parent is not None and (
                    sp.parent not in tv.spans
                    or tv.spans[sp.parent].opened is None):
                out.append({
                    "kind": "orphan_span", "trace": tid, "span": sid,
                    "host": host,
                    "detail": f"trace {tid}: span {sp.name}({sid}) "
                              f"claims parent {sp.parent!r} which was "
                              f"never opened"})
            if sp.closed is None:
                out.append({
                    "kind": "leaked_span", "trace": tid, "span": sid,
                    "name": sp.name, "host": host,
                    "detail": f"trace {tid}: span {sp.name}({sid}) "
                              f"opened on {host} but never closed"})
        for rec in tv.notes:
            sid = rec.get("span")
            if sid is not None and sid not in tv.spans:
                out.append({
                    "kind": "orphan_span", "trace": tid, "span": sid,
                    "host": rec.get("host"),
                    "detail": f"trace {tid}: note "
                              f"{rec.get('note')!r} anchored to "
                              f"unknown span {sid}"})
    return out


def _winner_attempt(root: Span) -> Span | None:
    for ch in root.children:
        if (ch.name == "attempt" and ch.closed is not None
                and ch.closed.get("status") == "ok"
                and not ch.closed.get("duplicate")):
            return ch
    return None


def _child(sp: Span, name: str) -> Span | None:
    for ch in sp.children:
        if ch.name == name and ch.closed is not None:
            return ch
    return None


def verify_traces(events: list, *, tol_ms: float = 5.0) -> list[dict]:
    """The trace-completeness contract over a merged stream:

      - every span anomaly (leaked/orphan) from ``span_anomalies``;
      - every *traced* ``router_admit`` resolves to exactly one
        ``request`` root span (``missing_root``/``multiple_root``),
        and that root closed (``incomplete_root``);
      - for each completed root whose winning attempt carries replica
        phases, ``wait_ms + queue + prefill`` agrees with the recorded
        queue-inclusive TTFT within ``tol_ms`` (``ttft_mismatch``) —
        the one-monotonic-clock invariant;
      - a closed serve span missing its queue/prefill phases is
        ``missing_phase`` (the decomposition would silently lie).

    Returns findings; [] means every admitted request's story is whole.
    """
    problems = span_anomalies(events)
    traces = build_traces(events)
    admits = [r for r in events
              if r.get("type") == "router_admit"
              and r.get("trace") is not None]
    for rec in admits:
        tid, rid = str(rec["trace"]), rec.get("id")
        tv = traces.get(tid)
        roots = [sp for sp in (tv.roots if tv else [])
                 if sp.name == "request"]
        if not roots:
            problems.append({
                "kind": "missing_root", "trace": tid, "id": rid,
                "detail": f"admitted rid {rid}: trace {tid} has no "
                          f"request root span"})
            continue
        if len(roots) > 1:
            problems.append({
                "kind": "multiple_root", "trace": tid, "id": rid,
                "detail": f"admitted rid {rid}: trace {tid} has "
                          f"{len(roots)} request root spans"})
            continue
        root = roots[0]
        if root.closed is None:
            problems.append({
                "kind": "incomplete_root", "trace": tid, "id": rid,
                "detail": f"admitted rid {rid}: request root span "
                          f"never closed (request lost or still "
                          f"in flight)"})
            continue
        ttft = root.closed.get("ttft_ms")
        wait = root.closed.get("wait_ms")
        attempt = _winner_attempt(root)
        if ttft is None or wait is None or attempt is None:
            continue
        serve = _child(attempt, "serve")
        if serve is None:
            continue  # unit-fleet transports answer without a replica
        queue, prefill = _child(serve, "queue"), _child(serve, "prefill")
        if queue is None or prefill is None:
            problems.append({
                "kind": "missing_phase", "trace": tid, "id": rid,
                "detail": f"rid {rid}: serve span closed without "
                          f"queue/prefill phase spans — the TTFT "
                          f"decomposition cannot be checked"})
            continue
        total = float(wait) + (queue.ms or 0.0) + (prefill.ms or 0.0)
        if abs(total - float(ttft)) > tol_ms:
            problems.append({
                "kind": "ttft_mismatch", "trace": tid, "id": rid,
                "ttft_ms": round(float(ttft), 3),
                "phase_sum_ms": round(total, 3),
                "detail": f"rid {rid}: phases sum to {total:.3f} ms "
                          f"(wait {float(wait):.3f} + queue "
                          f"{queue.ms:.3f} + prefill {prefill.ms:.3f}) "
                          f"but recorded TTFT is {float(ttft):.3f} ms "
                          f"(tol {tol_ms} ms) — a clock-source or "
                          f"accounting drift"})
    return problems


def critical_path(root: Span) -> list[Span]:
    """The chain of spans that gated completion: from the root, descend
    at each span into the child whose end gated its parent's close (the
    latest-ending child; an unclosed child gates forever).  The names on
    this path are the request's binding constraints — the thing the
    disaggregation roadmap item needs per-phase."""
    path, sp = [], root
    while sp is not None:
        path.append(sp)
        nxt, best = None, float("-inf")
        for ch in sp.children:
            if ch.opened is None:
                continue
            end = float("inf") if ch.closed is None else (ch.end_t()
                                                          or 0.0)
            if end > best:
                best, nxt = end, ch
        sp = nxt
    return path


def waterfall(root: Span) -> list[dict]:
    """Depth-first rows ``{"depth", "span"}`` in wall-clock open order —
    the renderer's input (``python -m tpuframe.obs trace``)."""
    rows: list[dict] = []

    def rec(sp: Span, depth: int) -> None:
        rows.append({"depth": depth, "span": sp})
        for ch in sorted(sp.children,
                         key=lambda c: float(
                             (c.opened or {}).get("t") or 0.0)):
            rec(ch, depth + 1)

    rec(root, 0)
    return rows


def trace_of(events: list, rid) -> str | None:
    """The trace id minted for ``rid``, from its ``router_admit``."""
    for r in events:
        if r.get("type") == "router_admit" and r.get("id") == rid:
            return r.get("trace")
    return None


# ---------------------------------------------------------------------------
# Analysis-gate self-check (``python -m tpuframe.analysis``).
# ---------------------------------------------------------------------------

def _rec(etype: str, t: float, host: str, **fields) -> dict:
    return {"schema": obs_events.SCHEMA_VERSION, "type": etype,
            "t": t, "host": host, "proc": 0, "attempt": 0, **fields}


def _synthetic_trace(tid: str = "tchk.0000") -> list[dict]:
    """One healthy end-to-end traced request, hand-built: router wait
    10 ms, replica queue 5 + prefill 2 + decode 40 — so the recorded
    queue-inclusive TTFT is exactly 17 ms.  The seeded positives below
    are mutations of this stream."""
    rh, ph = "checkh-p90", "checkh-p0"
    return [
        _rec("router_admit", 100.000, rh, id=1, trace=tid),
        _rec("span_open", 100.000, rh, trace=tid, span="r0",
             name="request", parent=None, rid=1),
        _rec("span_open", 100.010, rh, trace=tid, span="a1",
             name="attempt", parent="r0", replica="r0", cause="first"),
        _rec("span_open", 100.011, ph, trace=tid, span="s1",
             name="serve", parent="a1", rid=1),
        _rec("span_open", 100.016, ph, trace=tid, span="q1",
             name="queue", parent="s1"),
        _rec("span_close", 100.016, ph, trace=tid, span="q1", ms=5.0),
        _rec("span_open", 100.018, ph, trace=tid, span="p1",
             name="prefill", parent="s1"),
        _rec("span_close", 100.018, ph, trace=tid, span="p1", ms=2.0),
        _rec("span_open", 100.058, ph, trace=tid, span="d1",
             name="decode", parent="s1"),
        _rec("span_close", 100.058, ph, trace=tid, span="d1", ms=40.0,
             tokens=8),
        _rec("span_close", 100.059, ph, trace=tid, span="s1", ms=47.5,
             ttft_ms=7.0, tpot_ms=5.7),
        _rec("span_close", 100.061, rh, trace=tid, span="a1", ms=60.0,
             status="ok"),
        _rec("span_close", 100.062, rh, trace=tid, span="r0", ms=62.0,
             replica="r0", ttft_ms=17.0, wait_ms=10.0, tokens=8),
        _rec("router_request", 100.062, rh, id=1, replica="r0",
             ttft_ms=17.0, output_tokens=8, trace=tid, wait_ms=10.0),
    ]


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def check() -> list[str]:
    """Host-only tracing checks for the CI gate: the span schema pin,
    the TF123 emission-seam lint, seeded leaked/orphan/sum positives the
    verifier MUST flag (the gate refuses to run blind), the golden
    traced-fleet sample's full reconstruction, and the SLO sentry's
    parse + rc contract.  Returns problem strings; [] means healthy."""
    import pathlib

    problems: list[str] = []

    from tpuframe.obs import events as events_lib

    for etype, want in SPAN_REQUIRED_FIELDS.items():
        got = events_lib.REQUIRED_FIELDS.get(etype)
        if got is None:
            problems.append(
                f"span event type {etype!r} not registered in "
                f"obs.events.REQUIRED_FIELDS (TF112 contract)")
        elif tuple(got) != want:
            problems.append(
                f"span event {etype!r} required fields drifted: "
                f"registered {got!r}, tracing pins {want!r}")

    if not 0.0 <= resolve_sample() <= 1.0:
        problems.append(f"{ENV_SAMPLE} resolved outside [0, 1]")

    from tpuframe.analysis import source_lint

    pkg = pathlib.Path(__file__).resolve().parent.parent
    try:
        findings = source_lint.lint_paths([pkg])
    except Exception as exc:  # noqa: BLE001
        problems.append(f"trace lint crashed: {exc!r}")
        findings = []
    problems += [f"trace lint: {f}" for f in findings
                 if f.rule == "TF123"]

    # Synthetic round-trip: the healthy stream must verify clean with
    # exactly one complete root...
    healthy = _synthetic_trace()
    for p in verify_traces(healthy):
        problems.append(f"synthetic healthy trace flagged: "
                        f"[{p['kind']}] {p['detail']}")
    traces = build_traces(healthy)
    n_complete = sum(len(tv.complete_roots()) for tv in traces.values())
    if n_complete != 1:
        problems.append(f"synthetic trace reconstructed {n_complete} "
                        f"complete roots (want 1)")

    # ...and each seeded corruption MUST be flagged, or the verifier is
    # blind and every downstream assertion is theater.
    seeds = (
        ("leaked_span",
         [r for r in healthy
          if not (r["type"] == "span_close" and r.get("span") == "s1")]),
        ("orphan_span",
         [dict(r, parent="zz") if (r["type"] == "span_open"
                                   and r.get("span") == "s1") else r
          for r in healthy]),
        ("ttft_mismatch",
         [dict(r, ttft_ms=67.0) if (r["type"] == "span_close"
                                    and r.get("span") == "r0") else r
          for r in healthy]),
    )
    for kind, stream in seeds:
        if not any(p["kind"] == kind for p in verify_traces(stream)):
            problems.append(f"seeded {kind} positive NOT flagged — the "
                            f"trace gate is blind")

    # Golden traced-fleet sample: a real multi-process fleet run whose
    # reconstruction must stay whole (docs/samples/traced_fleet/, also
    # schema-validated by ``obs --selfcheck``).
    sample = os.path.join(_repo_root(), "docs", "samples",
                          "traced_fleet")
    files = events_lib.event_files(sample)
    if not files:
        problems.append(f"golden traced-fleet sample missing under "
                        f"{sample}")
    else:
        merged = events_lib.merge(sample)
        for p in verify_traces(merged):
            problems.append(f"traced-fleet sample: [{p['kind']}] "
                            f"{p['detail']}")
        gtraces = build_traces(merged)
        complete = [tv for tv in gtraces.values()
                    if tv.complete_roots()]
        if not complete:
            problems.append("traced-fleet sample: no complete request "
                            "root reconstructed")
        from tpuframe.obs import goodput as goodput_lib

        fleet = goodput_lib.fleet_stats(merged) or {}
        p99 = (fleet.get("ttft_exemplars") or {}).get("p99")
        if not p99 or p99.get("trace") not in gtraces:
            problems.append("traced-fleet sample: p99 exemplar does "
                            "not resolve to a reconstructed trace")

    # SLO sentry: defaults parse, and the rc contract holds on
    # synthetic streams (0 clean / 1 breach / 2 no data).
    from tpuframe.obs import slo as slo_lib

    try:
        specs = slo_lib.parse_slos(slo_lib.DEFAULT_SLO)
        windows = slo_lib.parse_windows(slo_lib.DEFAULT_WINDOWS)
    except ValueError as exc:
        problems.append(f"SLO defaults unparseable: {exc}")
        return problems
    fast = [_rec("router_request", 100.0 + 0.1 * i, "checkh-p90",
                 id=i, replica="r0", ttft_ms=10.0) for i in range(20)]
    slow = [dict(r, ttft_ms=10.0 * specs[0].threshold_ms)
            for r in fast]
    for name, stream, want in (("clean", fast, 0), ("breach", slow, 1),
                               ("empty", [], 2)):
        got = slo_lib.evaluate(stream, specs, windows)["rc"]
        if got != want:
            problems.append(f"SLO rc contract: {name} stream returned "
                            f"rc {got} (want {want})")
    return problems
