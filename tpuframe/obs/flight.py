"""Crash flight recorder — the last-N events, durable through any death.

The JSONL event log is append-per-record, but a hard crash can still
tear its final line mid-write, and the interesting records — the ones
just before the death — are exactly the ones at risk.  This module keeps
a bounded in-memory ring of every record the event layer builds (via
``events.add_listener``, which fires BEFORE the file write) and dumps it
as one small JSON file when something goes wrong:

  * the train loop's exception path (``train.py`` wraps the run),
  * SIGTERM/SIGINT preemption (``resilience/preempt.py``'s handler),
  * an injected ``kind=crash`` fault (``resilience/faults.py`` dumps
    right before its ``os._exit(42)`` — no exception handler can run),
  * the stall-abort anomaly path (``train.py:_on_stall``).

Dump layout (``flight_<attempt>.json``, ``.procN``-suffixed off the
primary process so multi-host dumps never clobber)::

    {"reason": "crash_injected", "t": ..., "host": ..., "proc": ...,
     "attempt": ..., "counters": {...obs.metrics snapshot...},
     "events": [...last N records, oldest first...]}

Ring capacity comes from ``TPUFRAME_FLIGHT_EVENTS`` (default 256).
Everything here is best-effort and stdlib-only: installed from signal
handlers and crash paths, it must never raise and never import jax.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from tpuframe.obs import events as events_lib

ENV_EVENTS = "TPUFRAME_FLIGHT_EVENTS"
DEFAULT_EVENTS = 256


class FlightRecorder:
    """Bounded ring of event records + the dump that survives a crash."""

    def __init__(self, directory: str, *, maxlen: int = DEFAULT_EVENTS):
        self.directory = directory
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, int(maxlen)))
        self._lock = threading.Lock()
        self.last_dump_path: str | None = None

    # -- listener target (events.add_listener) --------------------------

    def record(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    # -- the dump --------------------------------------------------------

    def dump(self, reason: str) -> str | None:
        """Write ``flight_<attempt>.json``; returns the path, or None on
        any failure.  Never raises — callers are signal handlers and
        crash paths mid-death."""
        try:
            proc = events_lib._process_index()
            suffix = f".proc{proc}" if proc else ""
            path = os.path.join(
                self.directory,
                f"flight_{events_lib.attempt_id()}{suffix}.json")
            payload = {
                "reason": reason,
                "t": round(time.time(), 3),
                "host": events_lib._hostname(),
                "proc": proc,
                "attempt": events_lib.attempt_id(),
                "counters": _counters(),
                "events": self.snapshot(),
            }
            os.makedirs(self.directory, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic: a dump is whole or absent
            # Benign single-writer publish: dump() runs on the crashing
            # thread; readers only see the path post-mortem, and taking
            # self._lock inside a signal handler could deadlock against
            # a record() mid-append on the interrupted thread.
            self.last_dump_path = path  # tf-lint: ok[TF114]
            return path
        except Exception:  # noqa: BLE001 — a failing dump must not turn
            return None  # a recoverable death into an unrecoverable one


def _counters() -> dict:
    try:
        from tpuframe.obs import metrics

        return metrics.counters()
    except Exception:  # noqa: BLE001 — interpreter teardown
        return {}


# ---------------------------------------------------------------------------
# Module-level singleton — crash paths reach it via sys.modules.get(...)
# (the preempt.py pattern) so no-jax/no-obs callers stay import-free.
# ---------------------------------------------------------------------------

_recorder: FlightRecorder | None = None


def install(directory: str | None = None,
            maxlen: int | None = None) -> FlightRecorder | None:
    """Start recording.  ``directory=None`` uses ``TPUFRAME_EVENTS_DIR``
    (the dump belongs next to the log it backs up); no directory at all
    means flight recording stays off."""
    global _recorder
    directory = directory or os.environ.get(events_lib.ENV_DIR, "")
    if not directory.strip():
        return None
    if maxlen is None:
        try:
            maxlen = int(os.environ.get(ENV_EVENTS, "") or DEFAULT_EVENTS)
        except ValueError:
            maxlen = DEFAULT_EVENTS
    uninstall()
    _recorder = FlightRecorder(directory, maxlen=maxlen)
    events_lib.add_listener(_recorder.record)
    return _recorder


def get() -> FlightRecorder | None:
    return _recorder


def dump(reason: str) -> str | None:
    """Dump the active recorder's ring; silent no-op when uninstalled."""
    rec = _recorder
    if rec is None:
        return None
    return rec.dump(reason)


def uninstall() -> None:
    global _recorder
    if _recorder is not None:
        events_lib.remove_listener(_recorder.record)
        _recorder = None
