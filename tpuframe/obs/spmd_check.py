"""SPMD-divergence debug checks (SURVEY.md §5.2).

Horovod needs a runtime coordinator to keep collective order identical on
every rank; compiled SPMD cannot reorder collectives, so the only remaining
divergence risk is *building different programs* on different hosts — a
config drift, a host-dependent code path, a non-deterministic data seed.
This module catches exactly that class in debug mode
(``TPUFRAME_CHECK_SPMD=1``): every host hashes its step program (lowered
StableHLO) and config, and the hashes are cross-checked with one small
allgather before training starts.
"""

from __future__ import annotations

import hashlib

import jax
import numpy as np


def digest(payload: bytes | str) -> np.ndarray:
    if isinstance(payload, str):
        payload = payload.encode()
    return np.frombuffer(hashlib.sha256(payload).digest(), np.uint8).copy()


def assert_uniform_across_hosts(tag: str, payload: bytes | str) -> None:
    """Raise RuntimeError if any host's payload hash differs (no-op
    single-process)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    mine = digest(payload)
    everyone = np.asarray(multihost_utils.process_allgather(mine))
    bad = [i for i in range(everyone.shape[0])
           if not np.array_equal(everyone[i], mine)]
    if bad:
        raise RuntimeError(
            f"SPMD divergence in {tag!r}: host {jax.process_index()} disagrees "
            f"with host(s) {bad} — hosts are about to run different programs. "
            f"Check for config drift / host-dependent branches / unseeded "
            f"randomness.")


def check_step_program(compiled_or_jitted, tag: str, *example_args,
                       budget=None) -> None:
    """Hash the step function's lowered StableHLO across hosts.

    ``lower()`` traces but does not backend-compile, so this is cheap enough
    for a startup debug check; the trace also warms nothing (jit caches by
    avals, and the same args are about to be used for real).

    ``budget``: an optional :class:`tpuframe.analysis.budgets.CommBudget`.
    When given, the same lowering is backend-compiled and its collectives
    are audited against the budget (see ``audit_step_program``) — the hash
    check and the collective audit run off one trace, so they cannot
    disagree about which program they inspected.
    """
    lowered = compiled_or_jitted.lower(*example_args)
    assert_uniform_across_hosts(f"{tag}:stablehlo", lowered.as_text())
    if budget is not None:
        audit_lowered(lowered, tag, budget)


def audit_lowered(lowered, tag: str, budget) -> None:
    """Compile an already-lowered step and check its collectives against a
    declared :class:`tpuframe.analysis.budgets.CommBudget`; raise
    RuntimeError on any violation.  Split out of ``check_step_program`` so
    single-host runs (where the hash allgather is a no-op) can still audit.
    """
    from tpuframe.analysis.budgets import check_budget
    from tpuframe.analysis.hlo_audit import audit_compiled

    report = audit_compiled(lowered.compile())
    violations = check_budget(report, budget)
    if violations:
        lines = "\n  ".join(violations)
        raise RuntimeError(
            f"collective budget violation in {tag!r} (budget "
            f"{budget.name!r}):\n  {lines}\n"
            f"wire summary: {report.summary()}")


def audit_step_program(compiled_or_jitted, tag: str, *example_args,
                       budget) -> None:
    """Startup collective-budget audit of a step program (no cross-host
    hash check) — ``check_step_program(..., budget=...)`` minus the
    allgather, for use outside ``TPUFRAME_CHECK_SPMD`` debug mode."""
    audit_lowered(compiled_or_jitted.lower(*example_args), tag, budget)
