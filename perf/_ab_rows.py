"""Supersession-aware parser for perf/results/offline_ab.jsonl.

PERF.md §11 invalidated every round-4 offline pallas row (interpret-mode
kernels lowered as XLA while loops — the census measured programs that
never run on chip) in favor of ``*_r5`` / ``*_v4222`` regenerations, and
regenerated rows are APPENDED to the jsonl with the same tag.  The rule,
shared by ``summarize_results.py`` and ``exp_offline_ab.py show`` and
pinned by tests/test_offline_ab_parser.py:

  - the program key is the row's ``(tag, policy)`` pair — ``policy`` is
    the optional remat-policy column the tpuframe.mem A/Bs write; rows
    without one key as ``(tag, None)``, so the pre-remat corpus parses
    exactly as before.  The LATEST line per key wins (a regeneration
    supersedes every earlier row with its key, including earlier
    ``compile_error`` rows — and a later compile_error likewise
    supersedes an earlier success: the latest compiler verdict is the
    verdict);
  - suffixed tags (``_r5``, ``_v4_221``, ...) are DISTINCT keys — a v4
    regeneration never hides the v5e row — and so are different remat
    policies under one tag: the ``none`` baseline row survives next to
    every searched-policy row.

Deliberately side-effect-free (no jax, no env scrub, no AOT lock):
tests and the summarizer import this without touching
``exp_offline_ab``'s module-level backend setup.
"""

from __future__ import annotations

import json


def parse_rows(lines) -> list:
    """Latest-wins filter over jsonl lines; returns the surviving record
    dicts in first-seen tag order.  Unparsable lines are skipped (the
    jsonl is append-only across crashes; a torn final line is normal)."""
    latest: dict = {}
    order: list = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict):
            continue
        key = (rec.get("tag", "?"), rec.get("policy"))
        if key not in latest:
            order.append(key)
        latest[key] = rec
    return [latest[k] for k in order]


def load_rows(path: str) -> list:
    with open(path) as f:
        return parse_rows(f)


def superseded_count(lines) -> int:
    """How many rows the latest-wins rule dropped (for report honesty:
    'N rows, M superseded' instead of a silently shrunken table)."""
    lines = list(lines)
    total = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            total += 1
    return total - len(parse_rows(lines))
