"""Perf experiment: per-step scalar-fetch sync vs async chained dispatch.

The round-2 bench hard-syncs every step (bench.py:143-150) because on the
axon relay `block_until_ready` on donated buffers was observed returning
early.  But fetching only the FINAL step's loss is also a full barrier for
the whole chain (each step consumes the previous state), while letting the
host run ahead and the device pipeline dispatch.  This measures both.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

jax.config.update("jax_compilation_cache_dir", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".xla_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from tpuframe import models
from tpuframe.models import losses
from tpuframe.parallel import step as step_lib

BATCH = int(os.environ.get("B", "512"))
STEPS = int(os.environ.get("N", "8"))
TRACE = os.environ.get("TRACE", "")


def log(m):
    print(f"[exp] {m}", file=sys.stderr, flush=True)


def main():
    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = rng.normal(0.5, 0.25, size=(BATCH, 224, 224, 3)).astype(jnp.bfloat16)
    y = rng.integers(0, 1000, size=(BATCH,)).astype(np.int32)
    variables = model.init(jax.random.key(0), jnp.asarray(x[:2]))
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    state = step_lib.TrainState.create(
        variables["params"], tx,
        model_state={"batch_stats": variables["batch_stats"]})
    train_step = step_lib.make_train_step(loss_fn, tx, None, donate=True)
    batch = {"image": jax.device_put(x), "label": jax.device_put(y)}

    log(f"compile+warmup batch={BATCH}")
    t0 = time.perf_counter()
    for i in range(3):
        state, metrics = train_step(state, batch)
        float(metrics["loss"])
    log(f"warmup done in {time.perf_counter()-t0:.1f}s")

    # Mode A: per-step scalar fetch (round-2 bench behavior)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, metrics = train_step(state, batch)
        float(metrics["loss"])
    dt_a = time.perf_counter() - t0
    log(f"A per-step sync : {STEPS*BATCH/dt_a:8.1f} img/s  ({dt_a/STEPS*1e3:.1f} ms/step)")

    # Mode B: async chain, single final fetch
    t0 = time.perf_counter()
    last = None
    for _ in range(STEPS):
        state, metrics = train_step(state, batch)
        last = metrics["loss"]
    float(last)
    dt_b = time.perf_counter() - t0
    log(f"B chained async : {STEPS*BATCH/dt_b:8.1f} img/s  ({dt_b/STEPS*1e3:.1f} ms/step)")

    # Mode C: block_until_ready on the final state (check the early-return claim)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, metrics = train_step(state, batch)
    jax.block_until_ready(state)
    dt_c = time.perf_counter() - t0
    log(f"C block_until_ready: {STEPS*BATCH/dt_c:8.1f} img/s  ({dt_c/STEPS*1e3:.1f} ms/step)")
    # sanity: fetch loss after, should be ~instant if C really waited
    t0 = time.perf_counter()
    float(metrics["loss"])
    log(f"C residual fetch after block: {time.perf_counter()-t0:.3f}s")

    if TRACE:
        log(f"tracing {STEPS} steps to {TRACE}")
        with jax.profiler.trace(TRACE):
            for _ in range(STEPS):
                state, metrics = train_step(state, batch)
            float(metrics["loss"])
        log("trace done")


if __name__ == "__main__":
    main()
