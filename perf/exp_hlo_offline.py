"""OFFLINE byte census: AOT-compile the exact ResNet-50 train step against
a v5e topology (compile-only libtpu, no chip/relay) and attribute the HBM
traffic from the optimized HLO.

Discovery (2026-07-31): the sandbox bundles `libtpu.so`, and
`jax.experimental.topologies.get_topology_desc("v5e:2x2", platform="tpu")`
yields compile-only TpuDevices — `jit(...).lower(...).compile()` then
produces the REAL TPU executable artifacts (optimized HLO with layouts,
`cost_analysis`, `memory_analysis`) on the CPU host.  This removes the
relay from the census's critical path entirely; `exp_hlo_dump.py` (the
on-chip twin, which kept hanging on the wedged relay) remains only as a
cross-check that the on-chip compiler makes the same choices.

Run from the repo root WITHOUT the axon platform:
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python perf/exp_hlo_offline.py

Outputs perf/results/resnet_step_hlo_offline.txt + a JSON summary line.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _common import (ensure_cpu_backend, hold_aot_lock,  # noqa: E402
                     to_shape_structs)

ensure_cpu_backend()
hold_aot_lock()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

BATCH = int(os.environ.get("B", "512"))
BN = os.environ.get("BN", "flax")   # flax | folded | fused (PERF.md §7 A/B)
REMAT = os.environ.get("REMAT", "0") == "1"
STEM = os.environ.get("STEM", "conv")
# Compile-only topology target.  "v5e:2x2" = the bench chip's family;
# "v4:2x2x2" = the north-star v4 family (32 GB HBM/chip, 275 TFLOPs
# bf16 peak — several v5e capacity verdicts flip there, VERDICT r4 #5).
TOPO = os.environ.get("TOPO", "v5e:2x2")


from _common import hlo_shape_census, hlo_nbytes  # noqa: E402


def log(m):
    print(f"[hlo-offline] {m}", file=sys.stderr, flush=True)


def main():
    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import step as step_lib

    log(f"building {TOPO} compile-only topology...")
    topo = topologies.get_topology_desc(TOPO, platform="tpu")
    dev = topo.devices[0]
    mesh = Mesh(np.array([dev]), ("data",))
    repl = NamedSharding(mesh, P())

    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16, bn=BN,
                            remat=REMAT, stem=STEM)
    log(f"model variant: bn={BN} remat={REMAT} stem={STEM}")
    # Abstract init on the CPU backend gives the param STRUCTURE; the AOT
    # compile only needs ShapeDtypeStructs.
    log("abstract-init model...")
    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((2, 224, 224, 3), jnp.bfloat16)),
        jax.random.key(0))
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(
            v["params"], tx, model_state={"batch_stats": v["batch_stats"]}),
        variables)
    train_step = step_lib.make_train_step(loss_fn, tx, None, donate=False)

    batch = {"image": jax.ShapeDtypeStruct((BATCH, 224, 224, 3), jnp.bfloat16,
                                           sharding=repl),
             "label": jax.ShapeDtypeStruct((BATCH,), jnp.int32, sharding=repl)}
    state = to_shape_structs(state, repl)

    log(f"AOT lower+compile (B={BATCH}) against {dev!r}...")
    compiled = jax.jit(train_step._fun if hasattr(train_step, "_fun")
                       else train_step).lower(state, batch).compile()

    ca = compiled.cost_analysis() or {}
    flops = ca.get("flops", 0.0)
    byts = ca.get("bytes accessed", 0.0)
    log(f"cost_analysis: flops={flops:.4g} bytes={byts:.4g} "
        f"({byts/1e9:.1f} GB/step, {byts/BATCH/1e6:.1f} MB/img)")
    try:
        ma = compiled.memory_analysis()
        log(f"memory: argument={ma.argument_size_in_bytes/1e9:.2f}GB "
            f"output={ma.output_size_in_bytes/1e9:.2f}GB "
            f"temp={ma.temp_size_in_bytes/1e9:.2f}GB")
    except Exception as e:  # noqa: BLE001
        log(f"memory_analysis unavailable: {e}")

    txt = compiled.as_text()
    suffix = "" if (BN, REMAT, STEM) == ("flax", False, "conv") else (
        f"_{BN}" + ("_remat" if REMAT else "") +
        ("_s2d" if STEM != "conv" else ""))
    from _common import topo_tag_suffix

    suffix += topo_tag_suffix(TOPO, "v5e:2x2")
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "results", f"resnet_step_hlo_offline{suffix}.txt")
    with open(out_path, "w") as f:
        f.write(txt)
    log(f"wrote {out_path} ({len(txt)/1e6:.1f} MB)")

    log("top shapes by total padded bytes (count x padded-est):")
    for k, n in hlo_shape_census(txt)[:25]:
        log(f"  {n:5d} x {k}  ~{hlo_nbytes(k)/1e6:.1f} MB each")

    print(json.dumps({"batch": BATCH, "bn": BN, "remat": REMAT, "stem": STEM,
                      "flops": flops, "bytes": byts,
                      "gb_per_step": round(byts / 1e9, 2),
                      "mb_per_image": round(byts / BATCH / 1e6, 2),
                      "hlo_chars": len(txt),
                      "source": f"offline AOT {TOPO} topology compile"}))


if __name__ == "__main__":
    main()
