"""Does Mosaic honor dot_general precision=HIGHEST inside a Pallas kernel?

If yes, the f32 flash-attention path could run with f32-true MXU products
(multi-pass) and the on-chip f32 tolerance in tests/test_flash_attention_tpu
could tighten from the bf16-product level (~4e-3) to ~1e-5.  This probes a
minimal kernel; the answer decides whether plumbing a precision arg through
flash_mha is worth it.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import make_log, setup

jax = setup()
import functools

import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

log = make_log("prec-probe")


def kernel(prec, x_ref, y_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        x_ref[...], y_ref[...], (((1,), (0,)), ((), ())),
        precision=prec, preferred_element_type=jnp.float32)


def run(prec):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(256, 256)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(256, 256)), jnp.float32)
    out = pl.pallas_call(
        functools.partial(kernel, prec),
        out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32),
    )(x, y)
    ref = np.asarray(x, np.float64) @ np.asarray(y, np.float64)
    err = float(np.max(np.abs(np.asarray(out, np.float64) - ref)))
    log(f"precision={prec}: max |err| vs f64 = {err:.3e}")
    return err


def main():
    log(f"backend={jax.default_backend()}")
    for prec in [None, jax.lax.Precision.DEFAULT, jax.lax.Precision.HIGHEST]:
        try:
            run(prec)
        except Exception as e:  # noqa: BLE001
            log(f"precision={prec}: FAILED {type(e).__name__}: {e}"[:300])


if __name__ == "__main__":
    main()
