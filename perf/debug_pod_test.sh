#!/bin/bash
# Debug driver for test_pod_config_multihost_kill_and_reshard_resume:
# runs the two phases with rank output teed to files so a hang is visible.
set -u
cd "$(dirname "$0")/.."
D=${D:-/tmp/podtest}
rm -rf "$D"; mkdir -p "$D"
PORT=$((20000 + RANDOM % 20000))

COMMON_ARGS=(-m tpuframe.train --config imagenet_resnet50_pod
  --set total_steps=8 --set ckpt_every=4 --set global_batch=32
  --set log_every=4 --set eval_every=1000 --set warmup_steps=2
  --set "compute_dtype='float32'"
  --set "dataset_kwargs={'image_size': 32, 'synthetic_size': 64, 'num_classes': 100}"
  --set "model_kwargs={'cifar_stem': True, 'num_classes': 100}"
  --ckpt-dir "$D/ck")

phase() { # name nprocs fault_step
  local name=$1 np=$2 fault=$3
  echo "=== phase $name: $np procs (fault=$fault) ==="
  local pids=()
  for pid in $(seq 0 $((np - 1))); do
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=2" \
    TPUFRAME_COORDINATOR=127.0.0.1:$PORT \
    TPUFRAME_NUM_PROCESSES=$np TPUFRAME_PROCESS_ID=$pid \
    TPUFRAME_FAULT_STEP=$fault \
    timeout 420 python "${COMMON_ARGS[@]}" \
      > "$D/$name.r$pid.out" 2> "$D/$name.r$pid.err" &
    pids+=($!)
  done
  local rc=0
  for p in "${pids[@]}"; do wait "$p" || rc=$?; done
  echo "phase $name done (last rc=$rc)"
}

phase p1 4 6
ls "$D/ck" || true
PORT=$((PORT + 1))
phase p2 2 ""
echo "=== p2 rank0 tail ==="; tail -5 "$D/p2.r0.out" "$D/p2.r0.err"
