"""Shared setup for the perf/ scripts: repo-root import path, persistent XLA
compile cache, stderr logging, and chained-async timing."""

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup():
    """Import-path + compile-cache config; call before importing tpuframe."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return jax


def make_log(tag: str):
    def log(m):
        print(f"[{tag}] {m}", file=sys.stderr, flush=True)

    return log


# NOTE: there is deliberately no repeat-the-same-call timer here: repeating
# an identical (program, inputs) pair on the axon relay is served by an
# execution cache in ~20us regardless of true cost (PERF.md §0b).  Timing is
# only valid through data-dependent chains (timeit_chain below) or loops
# that consume their own output (bench.py's state-chained loop).


def timeit_chain(make_chain, *args, chain: int = 16, reps: int = 3,
                 log=None, min_delta: float = 0.4, max_chain: int = 4096):
    """Execution-cache-proof timing for a pure function.

    ``make_chain(n)`` must return a jitted function of ``*args`` that runs
    the computation ``n`` times with a data dependence between iterations
    (lax.scan feeding output into input).  Per-iteration cost is
    (t_chainN - t_chain1) / (N - 1), best of ``reps``: the relay cannot
    cache across iterations (inputs differ), and dispatch/infeed overhead
    cancels in the difference.

    The chain GROWS (4x steps, up to ``max_chain``) until the measured
    difference clears ``min_delta`` seconds — the relay's round-trip jitter
    is ~100ms-class, so a fixed chain that is safe for a 20ms program is
    pure noise for a 0.2ms one.  Raw chain times go to ``log``."""
    import jax

    first, rest = args[0], args[1:]

    def best(f, salt):
        jax.block_until_ready(f(first, *rest))  # compile + settle
        ts = []
        for r in range(reps):
            # Fresh first-arg per timed call — an identical (program, inputs)
            # replay can be served by the relay's execution cache.  The
            # perturbation must be PERCENT-level: bf16 has ~2 significant
            # decimal digits, so an additive 1e-6 nudge rounds away and the
            # buffer stays bit-identical.
            a = jax.block_until_ready(first * (1.0 + 0.01 * (salt + r + 1)))
            t0 = time.perf_counter()
            jax.block_until_ready(f(a, *rest))
            ts.append(time.perf_counter() - t0)
        return ts

    t_1 = best(make_chain(1), 10)
    n = min(chain, max_chain)  # the caller's memory cap binds from the start
    while True:
        t_n = best(make_chain(n), 0)
        delta = min(t_n) - min(t_1)
        if log is not None:
            log(f"  raw chain{n}: {[round(t * 1e3, 1) for t in t_n]} ms; "
                f"chain1: {[round(t * 1e3, 1) for t in t_1]} ms "
                f"(delta {delta * 1e3:.1f} ms)")
        if delta >= min_delta:
            return delta / (n - 1)
        if n >= max_chain:
            # Refuse to return jitter as data (the failure mode this timer
            # exists to prevent); callers record the error row instead.
            raise RuntimeError(
                f"timeit_chain: delta {delta * 1e3:.1f} ms at chain {n} "
                f"never cleared min_delta {min_delta * 1e3:.0f} ms "
                f"(chain times {[round(t * 1e3, 1) for t in t_n]} ms vs "
                f"chain1 {[round(t * 1e3, 1) for t in t_1]} ms)")
        n = min(n * 4, max_chain)


# ---------------------------------------------------------------------------
# HLO text census (shared by exp_hlo_dump [on-chip] and exp_hlo_offline
# [AOT topology compile] so the two censuses can only disagree for
# compiler reasons, never tooling drift)
# ---------------------------------------------------------------------------

def hlo_shape_census(txt: str):
    """Group HLO tensor mentions by dtype/shape/layout, largest total
    padded bytes first.  TPU layouts look like
    ``bf16[512,112,112,64]{3,2,1,0:T(8,128)(2,1)}``."""
    import re

    shapes = re.findall(r"(bf16|f32|s32|u8|pred)\[([0-9,]*)\]\{([^}]*)\}", txt)
    census: dict = {}
    for dt, dims, layout in shapes:
        key = f"{dt}[{dims}]{{{layout}}}"
        census[key] = census.get(key, 0) + 1
    return sorted(census.items(), key=lambda kv: -hlo_nbytes(kv[0]) * kv[1])


def hlo_nbytes(key: str) -> float:
    """Padded-byte estimate for one census key: the layout's minor dim
    rounds to 128 lanes, the next-minor to 8 sublanes (the (8,128) tile;
    bf16's (2,1) sublane packing does not change the 8-row estimate)."""
    import re

    m = re.match(r"(bf16|f32|s32|u8|pred)\[([0-9,]*)\]\{([^:}]*)", key)
    if not m:
        return 0.0
    dt, dims, perm = m.groups()
    if not dims:
        return 0.0
    sz = {"bf16": 2, "f32": 4, "s32": 4, "u8": 1, "pred": 1}[dt]
    parts = [int(d) for d in dims.split(",") if d]
    if not parts:
        return 0.0
    try:
        mtm = [int(p) for p in perm.split(",") if p.strip() != ""]
    except ValueError:
        mtm = []
    if len(mtm) != len(parts):
        mtm = list(range(len(parts) - 1, -1, -1))
    padded = list(parts)
    if mtm:
        minor = mtm[0]
        padded[minor] = (padded[minor] + 127) // 128 * 128
        if len(mtm) > 1:
            nxt = mtm[1]
            padded[nxt] = (padded[nxt] + 7) // 8 * 8
    n = 1.0
    for d in padded:
        n *= d
    return n * sz


def ensure_cpu_backend():
    """Re-exec the current script on the plain CPU backend when the axon
    TPU plugin would otherwise register (it self-registers whenever
    PALLAS_AXON_POOL_IPS is set, even with JAX_PLATFORMS unset) — the
    offline AOT-census scripts must never touch the relay.  Call BEFORE
    importing jax."""
    import os
    import sys

    os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    # Pallas ops auto-interpret when the HOST backend is CPU — but these
    # scripts compile FOR a TPU topology, and an interpret-mode kernel
    # lowers as an XLA while loop, not a Mosaic custom call: the census
    # then measures a program that never runs on chip (discovered
    # round 5 — the first fused-conv-BN census was full of
    # FusedConvBN/while loops, and every round-4 offline "pallas" row
    # has the same defect).  Force real Mosaic lowering.
    os.environ.setdefault("TPUFRAME_PALLAS_INTERPRET", "0")
    if (os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu")
            or os.environ.get("PALLAS_AXON_POOL_IPS", "")):
        print("re-exec without axon platform...", flush=True)
        os.environ.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
        os.execvpe(sys.executable, [sys.executable] + sys.argv, os.environ)


def to_shape_structs(tree, sharding):
    """Map a pytree of shaped values (arrays or ShapeDtypeStructs, e.g.
    from jax.eval_shape) to sharding-annotated ShapeDtypeStructs for AOT
    lowering against a compile-only topology."""
    import jax

    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding)
        if hasattr(s, "shape") else s, tree,
        is_leaf=lambda l: isinstance(l, jax.ShapeDtypeStruct))


_AOT_LOCK_HANDLE = None


def _aot_lock_path():
    import os

    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".aot_compile.lock")


def aot_lock(timeout_s: float = 7200.0):
    """Context manager: acquire the machine-wide AOT-compile lock with a
    bounded wait (raises TimeoutError instead of hanging CI forever
    behind a long-running census)."""
    import contextlib
    import fcntl
    import time

    @contextlib.contextmanager
    def _cm():
        fh = open(_aot_lock_path(), "w")
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                try:
                    fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"AOT compile lock busy for >{timeout_s}s "
                            f"({_aot_lock_path()}) — another offline "
                            f"census/compile is holding it")
                    time.sleep(5.0)
            yield
        finally:
            fh.close()

    return _cm()


def hold_aot_lock():
    """Serialize compile-only libtpu users machine-wide.

    libtpu guards itself with a /tmp lockfile and ABORTS when a second
    process initializes concurrently (seen 2026-07-31: overlapping AOT
    censuses + the AOT guard tests).  Callers block here until the
    current holder exits; the lock is held for the process lifetime
    (the libtpu conflict window is the whole process, not just init).
    Call AFTER ensure_cpu_backend (so the re-exec doesn't drop it).
    """
    global _AOT_LOCK_HANDLE
    if _AOT_LOCK_HANDLE is not None:
        return
    import fcntl

    fh = open(_aot_lock_path(), "w")
    fcntl.flock(fh, fcntl.LOCK_EX)  # blocks until free
    _AOT_LOCK_HANDLE = fh


def topo_tag_suffix(topo: str, default: str) -> str:
    """Shared result-tag suffix for non-default compile-only topologies
    ("" for the default; "_v4_221"-style otherwise) — one rule for
    exp_hlo_offline / exp_capacity_audit / exp_offline_ab."""
    if topo == default:
        return ""
    return "_" + topo.replace(":", "_").replace("x", "")
