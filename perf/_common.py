"""Shared setup for the perf/ scripts: repo-root import path, persistent XLA
compile cache, stderr logging, and chained-async timing."""

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup():
    """Import-path + compile-cache config; call before importing tpuframe."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return jax


def make_log(tag: str):
    def log(m):
        print(f"[{tag}] {m}", file=sys.stderr, flush=True)

    return log


def timeit(fn, *args, steps: int = 10):
    """Async chained dispatch timing: warm twice, then `steps` dispatches and
    one final block (each call is independent here, so the block waits for
    the last dispatched program; see PERF.md §1 for the validation)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps
