"""Shared setup for the perf/ scripts: repo-root import path, persistent XLA
compile cache, stderr logging, and chained-async timing."""

import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup():
    """Import-path + compile-cache config; call before importing tpuframe."""
    if _REPO not in sys.path:
        sys.path.insert(0, _REPO)
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return jax


def make_log(tag: str):
    def log(m):
        print(f"[{tag}] {m}", file=sys.stderr, flush=True)

    return log


# NOTE: there is deliberately no repeat-the-same-call timer here: repeating
# an identical (program, inputs) pair on the axon relay is served by an
# execution cache in ~20us regardless of true cost (PERF.md §0b).  Timing is
# only valid through data-dependent chains (timeit_chain below) or loops
# that consume their own output (bench.py's state-chained loop).


def timeit_chain(make_chain, *args, chain: int = 16, reps: int = 3,
                 log=None, min_delta: float = 0.4, max_chain: int = 4096):
    """Execution-cache-proof timing for a pure function.

    ``make_chain(n)`` must return a jitted function of ``*args`` that runs
    the computation ``n`` times with a data dependence between iterations
    (lax.scan feeding output into input).  Per-iteration cost is
    (t_chainN - t_chain1) / (N - 1), best of ``reps``: the relay cannot
    cache across iterations (inputs differ), and dispatch/infeed overhead
    cancels in the difference.

    The chain GROWS (4x steps, up to ``max_chain``) until the measured
    difference clears ``min_delta`` seconds — the relay's round-trip jitter
    is ~100ms-class, so a fixed chain that is safe for a 20ms program is
    pure noise for a 0.2ms one.  Raw chain times go to ``log``."""
    import jax

    first, rest = args[0], args[1:]

    def best(f, salt):
        jax.block_until_ready(f(first, *rest))  # compile + settle
        ts = []
        for r in range(reps):
            # Fresh first-arg per timed call — an identical (program, inputs)
            # replay can be served by the relay's execution cache.  The
            # perturbation must be PERCENT-level: bf16 has ~2 significant
            # decimal digits, so an additive 1e-6 nudge rounds away and the
            # buffer stays bit-identical.
            a = jax.block_until_ready(first * (1.0 + 0.01 * (salt + r + 1)))
            t0 = time.perf_counter()
            jax.block_until_ready(f(a, *rest))
            ts.append(time.perf_counter() - t0)
        return ts

    t_1 = best(make_chain(1), 10)
    n = min(chain, max_chain)  # the caller's memory cap binds from the start
    while True:
        t_n = best(make_chain(n), 0)
        delta = min(t_n) - min(t_1)
        if log is not None:
            log(f"  raw chain{n}: {[round(t * 1e3, 1) for t in t_n]} ms; "
                f"chain1: {[round(t * 1e3, 1) for t in t_1]} ms "
                f"(delta {delta * 1e3:.1f} ms)")
        if delta >= min_delta:
            return delta / (n - 1)
        if n >= max_chain:
            # Refuse to return jitter as data (the failure mode this timer
            # exists to prevent); callers record the error row instead.
            raise RuntimeError(
                f"timeit_chain: delta {delta * 1e3:.1f} ms at chain {n} "
                f"never cleared min_delta {min_delta * 1e3:.0f} ms "
                f"(chain times {[round(t * 1e3, 1) for t in t_n]} ms vs "
                f"chain1 {[round(t * 1e3, 1) for t in t_1]} ms)")
        n = min(n * 4, max_chain)
