#!/bin/bash
# Round-3 TPU workload queue: waits (patiently, ONE client) for the wedged
# relay to free, then runs every chip-blocked deliverable serially.
# Results land in perf/results/. See PERF.md §0 for the relay constraints.
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_all.log
echo "=== run_all_tpu $(date -u +%FT%TZ) ===" >> "$LOG"

note() { echo "[run_all $(date -u +%T)] $*" | tee -a "$LOG"; }

# Phase 0: the patient claim. A single python process waits for the grant;
# no timeout-kill cycles (killed clients are what wedged the relay).
note "phase 0: waiting for chip claim (up to 200 min)..."
timeout 12000 python -u -c "
import time; t0=time.time()
import jax, jax.numpy as jnp
(jnp.ones((256,256), jnp.bfloat16) @ jnp.ones((256,256), jnp.bfloat16)).block_until_ready()
print(f'CLAIM OK after {time.time()-t0:.1f}s', flush=True)
" >> "$LOG" 2>&1
rc=$?
if [ $rc -ne 0 ]; then
  note "phase 0 FAILED rc=$rc — relay still wedged; giving up"
  exit 1
fi
note "chip is back — running the queue"

run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  note "START $name"
  timeout "$tmo" "$@" > "perf/results/$name.out" 2> "perf/results/$name.err"
  note "END $name rc=$?"
}

# 1. Headline bench, current default config (async timing).
run bench_default 1800 python bench.py
# 2. Batch re-sweep under async timing.
TPUFRAME_BENCH_BATCH=768  run bench_b768  1200 python bench.py
TPUFRAME_BENCH_BATCH=1024 run bench_b1024 1200 python bench.py
TPUFRAME_BENCH_BATCH=256  run bench_b256  1200 python bench.py
# 3. Space-to-depth stem A/B at the best-known batch.
TPUFRAME_BENCH_STEM=space_to_depth run bench_s2d 1200 python bench.py
# 4. On-chip flash-attention proof (non-interpreted Mosaic).
TPUFRAME_TPU_TESTS=1 run fa_tpu_tests 2400 \
    python -m pytest tests/test_flash_attention_tpu.py -v
# 5. Pallas-vs-XLA attention sweep, seq 2k-8k.
run attn_bench 2400 python perf/bench_attention.py
# 6. Transformer step throughput (BERT + LM, both impls).
run tf_bench 2400 python perf/bench_transformer.py
# 7. Step-cost breakdown for PERF.md §2.
run breakdown 1800 python perf/exp_breakdown.py

note "queue complete"
