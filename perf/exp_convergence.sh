#!/bin/bash
# Convergence-shaped on-chip proof (round-4; BASELINE.json:5 "converges",
# SURVEY.md §5.3/§5.4): single-step correctness tests cannot demonstrate
# sustained training.  Two runs:
#
#  A. cifar10_resnet18 (synthetic, learnable class templates), 600 steps:
#     async checkpoints every 150, injected crash (os._exit) at step 350,
#     claim-retry, resume from ckpt-300, continue to 600.  Assertions
#     (exp_convergence_check.py): loss curve decreasing across the kill,
#     resume continues the curve, throughput steady.
#  B. imagenet_resnet50 (synthetic), 300 sustained steps at batch 256 —
#     the bench workload running through the REAL harness + input pipeline;
#     steady-state throughput recorded vs bench.py's device-only number.
#
# Relay rules (PERF.md §0): ONE client at a time, strictly serial.  The
# phase-A crash (os._exit skips client teardown) may wedge the chip grant
# for ~10 min — the phase-B/resume claim loops retry patiently.
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/convergence.log
CKPT=perf/results/conv_ckpt
. perf/claim.sh
note() { echo "[conv $(date -u +%T)] $*" | tee -a "$LOG"; }

echo "=== exp_convergence $(date -u +%FT%TZ) ===" >> "$LOG"
rm -rf "$CKPT" "$CKPT-r50" perf/results/conv_a.jsonl \
       perf/results/conv_b.jsonl perf/results/conv_r50.jsonl

# augment='none': the curve criteria in exp_convergence_check.py were
# validated (round 4, CPU) on the unaugmented recipe; the round-5
# augmentation default would shift the 600-step loss floor and the
# experiment's job is crash/resume + curve mechanics, not recipe quality.
CIFAR_ARGS=(--config cifar10_resnet18
  --set total_steps=600 --set warmup_steps=50 --set ckpt_every=150
  --set ckpt_async=True --set log_every=10 --set eval_every=300
  --set eval_batches=4 --set augment="'none'" --ckpt-dir "$CKPT")

queue_should_stop && { note "STOP sentinel present; exiting"; exit 0; }
note "phase A: cifar10_resnet18, crash injected at step 350"
TPUFRAME_FAULT_STEP=350 TPUFRAME_FAULT_ONCE=1 \
  timeout 2400 python -m tpuframe.train "${CIFAR_ARGS[@]}" \
  --log-file perf/results/conv_a.jsonl \
  > perf/results/conv_a.out 2>&1
rc=$?
note "phase A exited rc=$rc (expect 42 = injected crash)"

note "phase A2: re-claim after the crash (grant may be wedged ~10min)"
claim_chip 40 "$LOG" || { note "re-claim FAILED; aborting"; exit 1; }

queue_should_stop && { note "STOP sentinel present; exiting"; exit 0; }
note "phase B: resume from last committed ckpt, run to step 600"
timeout 2400 python -m tpuframe.train "${CIFAR_ARGS[@]}" \
  --log-file perf/results/conv_b.jsonl \
  > perf/results/conv_b.out 2>&1
note "phase B exited rc=$?"

queue_should_stop && { note "STOP sentinel present; exiting"; exit 0; }
note "phase C: imagenet_resnet50 synthetic, 300 sustained steps @ batch 256"
timeout 3000 python -m tpuframe.train --config imagenet_resnet50 \
  --set total_steps=300 --set warmup_steps=50 --set global_batch=256 \
  --set log_every=10 --set eval_every=10000 --set ckpt_every=10000 \
  --set "dataset_kwargs={'synthetic_size': 1024, 'keep_u8': True}" \
  --ckpt-dir "$CKPT-r50" --log-file perf/results/conv_r50.jsonl \
  > perf/results/conv_r50.out 2>&1
note "phase C exited rc=$?"

note "phase D: analysis"
python perf/exp_convergence_check.py | tee perf/results/conv_summary.json
note "exp_convergence done"
