#!/bin/bash
# Round-3 TPU queue #3: fused-xent A/B + long-context LM on the chip.
#  - LM 124M seq 2048: dense vs fused-xent loss path (both attention impls)
#  - seq 8192: pallas flash vs xla attention (xla expected to OOM/compile-fail
#    — that negative result is the flash memory win, record it)
#  - seq 32768 b=1: pallas + fused-xent (the lm_long flagship shape)
# Same relay rules: ONE client, strictly serial; patient retry claim.
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_all3.log
echo "=== run_all_tpu3 $(date -u +%FT%TZ) ===" >> "$LOG"

note() { echo "[run_all3 $(date -u +%T)] $*" | tee -a "$LOG"; }

note "phase 0: probing for chip claim (retry loop, up to ~5h)..."
claimed=0
for attempt in $(seq 1 60); do
  timeout 2400 python -u -c "
import time; t0=time.time()
import jax, jax.numpy as jnp
(jnp.ones((256,256), jnp.bfloat16) @ jnp.ones((256,256), jnp.bfloat16)).block_until_ready()
print(f'CLAIM OK after {time.time()-t0:.1f}s', flush=True)
" >> "$LOG" 2>&1 && { claimed=1; break; }
  note "claim attempt $attempt failed; sleeping 180s"
  sleep 180
done
if [ "$claimed" != 1 ]; then
  note "phase 0 FAILED — relay wedged for the whole window; giving up"
  exit 1
fi
note "chip claimed — running queue 3"

run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  note "START $name"
  timeout "$tmo" "$@" > "perf/results/$name.out" 2> "perf/results/$name.err"
  note "END $name rc=$?"
}

# 1. Fused-xent A/B at the standard shape (dense numbers exist from queue 1).
MODEL=lm XENT=fused run tf_lm_fusedxent 2400 python perf/bench_transformer.py
# 2. Long context 8k: both attention impls, fused xent (xla attn may OOM).
MODEL=lm XENT=fused LM_BATCH=2 LM_SEQ=8192 \
    run tf_lm_8k 2400 python perf/bench_transformer.py
# 3. The 32k flagship shape, pallas-only (xla attn cannot fit).
MODEL=lm XENT=fused LM_BATCH=1 LM_SEQ=32768 ATTN_ONLY=pallas \
    run tf_lm_32k 2400 python perf/bench_transformer.py
# 4. BERT at bigger batch (43% MFU at b=128 — check b=256 headroom).
MODEL=bert BERT_BATCH=256 run tf_bert_b256 1800 python perf/bench_transformer.py
# 5. remat off at the standard LM shape (activations fit at b8 s2048;
#    saves the recompute the queue-1 number paid).
MODEL=lm XENT=fused REMAT=0 run tf_lm_noremat 2400 python perf/bench_transformer.py
# 6. remat-off dense for an apples-to-apples xent A/B at the same settings.
MODEL=lm REMAT=0 run tf_lm_noremat_dense 2400 python perf/bench_transformer.py

# 7. Live autotune demo: tiny budgeted sweep of the fusion knob at batch 256
#    (short bench: 4 measure steps) — the SURVEY §3b autotune row, running.
#    Per-trial timeout 900s < wrapper 4200s so a slow trial is dropped by
#    the sweep (recorded as failed) instead of the wrapper killing the whole
#    run before the report is written.
TPUFRAME_BENCH_BATCH=256 TPUFRAME_BENCH_STEPS=8 TPUFRAME_BENCH_WARMUP=2 \
    TPUFRAME_BENCH_BUDGET_S=850 \
    run autotune_demo 4200 python -m tpuframe.obs.autotune \
    --out perf/results/autotune_report.json --budget 4 --timeout 900 \
    --axis "TPUFRAME_FUSION_THRESHOLD=,0,67108864" \
    -- python bench.py
note "queue 3 complete (incl. autotune demo)"
