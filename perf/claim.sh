# Shared TPU-relay claim helpers — source this, don't run it.
#
# Relay rules (PERF.md §0): ONE client at a time; never kill a client
# mid-claim (a killed client wedges the chip grant for 10+ min); a wedged
# relay raises UNAVAILABLE from backend init only after ~25 min of
# internal retries, so claims are patient clean-exiting probes in a retry
# loop rather than a single blocking attempt.
#
# claim_wait_for_others        — block until no other claim probe is live
#                                (the one-client rule across queues).
# claim_chip [attempts] [log]  — retry loop; returns 0 once a probe claims
#                                the chip, 1 if every attempt failed.
# The probe's "CLAIM OK after" marker text is load-bearing: it is both the
# success line in the logs and the pgrep signature claim_wait_for_others
# scans for.

CLAIM_MARKER="CLAIM OK after"
# Graceful halt: touch this file and every queue exits before its next
# claim attempt or benchmark run (so e.g. the driver's end-of-round
# bench.py is never blocked behind a queue's chip claim).
STOP_SENTINEL="perf/STOP"

queue_should_stop() { [ -e "$STOP_SENTINEL" ]; }

relay_up() {
  # Fast tunnel-port probe (the outage signature: every port refuses
  # instantly — same check bench.py does pre-import).  Exit 0 = some
  # port accepts TCP.
  python - <<'PYEOF'
import socket, sys
for port in (8083, 8082, 8081):
    s = socket.socket(); s.settimeout(2.0)
    try:
        s.connect(("127.0.0.1", port)); sys.exit(0)
    except OSError:
        continue
    finally:
        s.close()
sys.exit(1)
PYEOF
}

claim_wait_for_others() {
  # A sourcing script's own cmdline never contains the marker (it lives
  # only inside the probe's python -c), and this runs before that script
  # launches its own probe, so a plain pgrep is self-exclusion-safe.
  while pgrep -f "$CLAIM_MARKER" > /dev/null; do
    echo "[claim $(date -u +%T)] waiting for another queue's claim probe..."
    sleep 60
  done
}

claim_chip() { # [attempts=60] [logfile=/dev/stdout]
  local attempts=${1:-60} log=${2:-/dev/stdout} attempt
  for attempt in $(seq 1 "$attempts"); do
    if queue_should_stop; then
      echo "[claim $(date -u +%T)] STOP sentinel present; aborting claim" \
        | tee -a "$log"
      return 1
    fi
    timeout 2400 python -u -c "
import time; t0=time.time()
import jax, jax.numpy as jnp
(jnp.ones((256,256), jnp.bfloat16) @ jnp.ones((256,256), jnp.bfloat16)).block_until_ready()
print(f'$CLAIM_MARKER {time.time()-t0:.1f}s', flush=True)
" >> "$log" 2>&1 && return 0
    echo "[claim $(date -u +%T)] attempt $attempt failed; sleeping 180s" \
      | tee -a "$log"
    sleep 180
  done
  return 1
}
