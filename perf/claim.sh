# Shared TPU-relay claim helpers — source this, don't run it.
#
# Relay rules (PERF.md §0): ONE client at a time; never kill a client
# mid-claim (a killed client wedges the chip grant for 10+ min); a wedged
# relay raises UNAVAILABLE from backend init only after ~25 min of
# internal retries, so claims are patient clean-exiting probes in a retry
# loop rather than a single blocking attempt.
#
# claim_wait_for_others        — block until no other claim probe is live
#                                (the one-client rule across queues).
# claim_chip [attempts] [log]  — retry loop; returns 0 once a probe claims
#                                the chip, 1 if every attempt failed.
# The probe's "CLAIM OK after" marker text is load-bearing: it is both the
# success line in the logs and the pgrep signature claim_wait_for_others
# scans for.

CLAIM_MARKER="CLAIM OK after"
# Graceful halt: touch this file and every queue exits before its next
# claim attempt or benchmark run (so e.g. the driver's end-of-round
# bench.py is never blocked behind a queue's chip claim).
STOP_SENTINEL="perf/STOP"

queue_should_stop() { [ -e "$STOP_SENTINEL" ]; }

relay_up() {
  # Fast tunnel-port probe, mirroring bench.py's _relay_probe guards:
  # only meaningful in the loopback-relay environment (fail-open
  # elsewhere — a "down" verdict must never be fabricated on setups
  # where nothing listens on localhost by design).  Exit 0 = up/unknown.
  [ "${AXON_LOOPBACK_RELAY:-}" = "1" ] || return 0
  local host="${PALLAS_AXON_POOL_IPS%%,*}"
  python - "${host:-127.0.0.1}" <<'PYEOF'
import socket, sys
for port in (8083, 8082, 8081):
    s = socket.socket(); s.settimeout(2.0)
    try:
        s.connect((sys.argv[1], port)); sys.exit(0)
    except OSError:
        continue
    finally:
        s.close()
sys.exit(1)
PYEOF
}

run_failed_by_outage() { # rc errfile — did this failure look like an outage?
  local rc=$1 err=$2
  [ "$rc" = 0 ] && return 1
  relay_up || return 0                # mode 1: tunnel ports refusing
  # mode 2: wedged-but-listening — backend init raises UNAVAILABLE after
  # ~25 min of internal retries (claim.sh header).  A stray UNAVAILABLE
  # in an unrelated failure just costs one harmless retry.
  [ -f "$err" ] && tail -c 4000 "$err" \
    | grep -q "Unable to initialize backend\|UNAVAILABLE" && return 0
  # mode 3: timeout kill (rc 124).  Observed 2026-07-31: a SIGTERM'd
  # client wedges the grant such that the NEXT client hangs in backend
  # init with the tunnel ports still listening and no UNAVAILABLE within
  # a 20-min timeout — every later run then burns its full timeout.  A
  # timeout is treated as outage-suspect: the re-claim probe is ~10s when
  # the relay is actually healthy, so the false-positive cost is one
  # retry of a genuinely-slow run.
  [ "$rc" = 124 ] && return 0
  return 1
}

queue_run() { # name timeout cmd...  (expects caller-defined note() + $LOG)
  local name=$1 tmo=$2; shift 2
  if queue_should_stop; then
    note "STOP sentinel present; skipping $name and exiting"
    exit 0
  fi
  # Preserve a prior result before the redirect truncates it: a rerun
  # that hangs on a dead relay must not destroy the only committed
  # measurement (this happened to round-3's bench_b256.out on
  # 2026-07-31; restored from git).
  if [ -s "perf/results/$name.out" ]; then
    cp "perf/results/$name.out" "perf/results/$name.out.prev"
  fi
  note "START $name"
  timeout "$tmo" "$@" > "perf/results/$name.out" 2> "perf/results/$name.err"
  local rc=$?
  note "END $name rc=$rc"
  # Mid-queue outage: without this, every later run burns its whole
  # timeout against a dead relay (round 3's queue-1→outage transition).
  # One-client rule holds on re-claim, and the failed run is retried
  # once so its data point isn't silently lost.
  if run_failed_by_outage "$rc" "perf/results/$name.err"; then
    note "outage signature after $name (rc=$rc) — re-claiming chip"
    claim_wait_for_others | tee -a "$LOG"
    if ! claim_chip 96 "$LOG"; then
      note "re-claim FAILED; giving up"
      exit 1
    fi
    note "chip re-claimed — retrying $name once"
    timeout "$tmo" "$@" > "perf/results/$name.out" 2> "perf/results/$name.err"
    rc=$?
    note "END $name (retry) rc=$rc"
  fi
  # Failed final attempt (even with partial output): put the preserved
  # result back so the artifact always carries the best available
  # measurement.  .prev is transient — deleted on both paths, so a stale
  # backup can never masquerade as a later round's data.
  if [ "$rc" != 0 ] && [ -s "perf/results/$name.out.prev" ]; then
    note "restoring prior $name.out (final rc=$rc)"
    cp "perf/results/$name.out.prev" "perf/results/$name.out"
  fi
  rm -f "perf/results/$name.out.prev"
}

claim_wait_for_others() {
  # A sourcing script's own cmdline never contains the marker (it lives
  # only inside the probe's python -c), and this runs before that script
  # launches its own probe, so a plain pgrep is self-exclusion-safe.
  while pgrep -f "$CLAIM_MARKER" > /dev/null; do
    echo "[claim $(date -u +%T)] waiting for another queue's claim probe..."
    sleep 60
  done
}

claim_chip() { # [attempts=60] [logfile=/dev/stdout]
  local attempts=${1:-60} log=${2:-/dev/stdout} attempt
  for attempt in $(seq 1 "$attempts"); do
    if queue_should_stop; then
      echo "[claim $(date -u +%T)] STOP sentinel present; aborting claim" \
        | tee -a "$log"
      return 1
    fi
    timeout 2400 python -u -c "
import time; t0=time.time()
import jax, jax.numpy as jnp
(jnp.ones((256,256), jnp.bfloat16) @ jnp.ones((256,256), jnp.bfloat16)).block_until_ready()
print(f'$CLAIM_MARKER {time.time()-t0:.1f}s', flush=True)
" >> "$log" 2>&1 && return 0
    echo "[claim $(date -u +%T)] attempt $attempt failed; sleeping 180s" \
      | tee -a "$log"
    sleep 180
  done
  return 1
}
