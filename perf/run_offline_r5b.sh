#!/bin/bash
# Round-5 offline queue, part B: the v4-family passes with the RIGHT
# slice shapes (v4 exposes 2 devices per chip: v4:2x2x1 = 8 devices for
# the capacity audit; v4:2x2x4 = 32 devices = the v4-32 north star for
# the DP-32 program).  Part A's v4:2x2x2 audit run hit 16 devices and
# recorded honest mesh-mismatch error rows.
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_offline_r5.log
note() { echo "[offline-r5b $(date -u +%T)] $*" | tee -a "$LOG"; }

run() { # name cmd...
  local name=$1; shift
  note "START $name"
  env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu timeout 5400 "$@" \
      > "perf/results/$name.out" 2> "perf/results/$name.err"
  note "END $name rc=$?"
}

run v4_capacity_all_b env TOPO=v4:2x2x1 python perf/exp_capacity_audit.py all
run v4_dp32 env TOPO=v4:2x2x4 python perf/exp_offline_ab.py dp32
run v4_hlo_b512_fused env TOPO=v4:2x2x2 B=512 BN=fused python perf/exp_hlo_offline.py

note "offline r5b queue complete"
