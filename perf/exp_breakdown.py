"""Perf experiment: where does the ResNet-50 step time go?

Device tracing is unavailable on the axon relay (jax.profiler.start_trace
hangs before returning — see PERF.md), so this decomposes the step cost by
compiling and timing nested sub-programs:

  fwd            : inference forward (train=False)
  fwd_train      : forward with batch-stat mutation
  grad           : value_and_grad (fwd+bwd), no optimizer
  full           : the real train step (grad + pmean-less update)

and prints XLA cost analysis (flops / bytes accessed) for each, which gives
an analytic roofline: t_mxu = flops / 197e12, t_hbm = bytes / 8.1e11 (v5e).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

jax.config.update("jax_compilation_cache_dir", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".xla_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from tpuframe import models
from tpuframe.models import losses
from tpuframe.parallel import step as step_lib

BATCH = int(os.environ.get("B", "512"))
STEPS = int(os.environ.get("N", "8"))


def log(m):
    print(f"[exp] {m}", file=sys.stderr, flush=True)


def time_fn(make_chain, *args):
    """Per-iteration time of a data-dependent chain (perf/_common.py).

    chain=16: the difference t_16 - t_1 must clear the relay's ~100ms-class
    round-trip jitter even for the ~20ms fwd program (the first chain=8 run
    got clamped to 0 for exactly that reason)."""
    from _common import timeit_chain

    return timeit_chain(make_chain, *args, chain=16, log=log)


def cost(compiled):
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return ca.get("flops", 0), ca.get("bytes accessed", 0)
    except Exception:
        return 0, 0


def main():
    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0.5, 0.25, size=(BATCH, 224, 224, 3)),
                    jnp.bfloat16)
    y = jnp.asarray(rng.integers(0, 1000, size=(BATCH,)), jnp.int32)
    variables = model.init(jax.random.key(0), x[:2])
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
    params, bstats = variables["params"], variables["batch_stats"]

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    state = step_lib.TrainState.create(
        params, tx, model_state={"batch_stats": bstats})
    train_step = step_lib.make_train_step(loss_fn, tx, None, donate=False)
    batch = {"image": x, "label": y}

    # Sub-program timings must be DATA-DEPENDENT chains (lax.scan feeding a
    # 1e-30-scaled summary of iteration i's output into iteration i+1's
    # input): repeating an identical (program, inputs) dispatch is served by
    # the relay's execution cache in ~20us regardless of true cost (PERF.md
    # §0b).  1e-30 keeps the carry numerically unchanged in bf16 while
    # remaining opaque to XLA's simplifier.  Per-iteration time comes from
    # timeit_chain's (t_N - t_1)/(N-1) difference.

    # -- fwd (inference) --
    def fwd_chain(n):
        def g(im, p, s):
            def body(xc, _):
                logits = model.apply({"params": p, **s}, xc, train=False)
                dep = (1e-30 * jnp.sum(logits)).astype(xc.dtype)
                return xc + dep, None
            xc, _ = jax.lax.scan(body, im, None, length=n)
            return xc
        return jax.jit(g)

    log("timing fwd(infer)...")
    t = time_fn(fwd_chain, x, params, {"batch_stats": bstats})
    fwd = jax.jit(lambda p, s, im: model.apply(
        {"params": p, **s}, im, train=False))
    log("cost-analysis fwd(infer)...")
    c = cost(fwd.lower(params, {"batch_stats": bstats}, x).compile())
    log(f"fwd(infer)  : {t*1e3:7.1f} ms  flops={c[0]:.3e} bytes={c[1]:.3e}")

    # -- fwd train (batch stats) --
    def fwd_t_chain(n):
        def g(im, p, s):
            def body(carry, _):
                xc, stats = carry
                logits, mutated = model.apply(
                    {"params": p, **stats}, xc, train=True,
                    mutable=["batch_stats"])
                dep = (1e-30 * jnp.sum(logits)).astype(xc.dtype)
                return (xc + dep, dict(mutated)), None
            (xc, _), _ = jax.lax.scan(body, (im, s), None, length=n)
            return xc
        return jax.jit(g)

    log("timing fwd(train)...")
    t = time_fn(fwd_t_chain, x, params, {"batch_stats": bstats})
    fwd_t = jax.jit(lambda p, s, im: model.apply(
        {"params": p, **s}, im, train=True, mutable=["batch_stats"]))
    log("cost-analysis fwd(train)...")
    c = cost(fwd_t.lower(params, {"batch_stats": bstats}, x).compile())
    log(f"fwd(train)  : {t*1e3:7.1f} ms  flops={c[0]:.3e} bytes={c[1]:.3e}")

    # -- grad --
    r = jax.random.key(1)

    def grad_chain(n):
        def g(im, p, s):
            def body(carry, _):
                xc, stats = carry
                (loss, (stats, _)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(
                        p, stats, {"image": xc, "label": y}, r)
                gsum = sum(jnp.sum(g.astype(jnp.float32))
                           for g in jax.tree.leaves(grads))
                dep = (1e-30 * (loss + gsum)).astype(xc.dtype)
                return (xc + dep, stats), None
            (xc, _), _ = jax.lax.scan(body, (im, s), None, length=n)
            return xc
        return jax.jit(g)

    log("timing grad...")
    t = time_fn(grad_chain, x, params, {"batch_stats": bstats})

    def just_grad(p, s, b, r):
        return jax.value_and_grad(loss_fn, has_aux=True)(p, s, b, r)
    gr = jax.jit(just_grad)
    log("cost-analysis grad...")
    c = cost(gr.lower(params, {"batch_stats": bstats}, batch, r).compile())
    log(f"grad(f+b)   : {t*1e3:7.1f} ms  flops={c[0]:.3e} bytes={c[1]:.3e}")

    # -- full step --
    log("timing full step...")
    new, m = train_step(state, batch)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    # Seed with the warmup's OUTPUT: restarting from `state` would make
    # timed iteration 0 a bit-identical replay of the warmup dispatch,
    # which the relay's execution cache serves in ~20us (PERF.md §0b).
    cur = new
    for _ in range(STEPS):
        cur, m = train_step(cur, batch)
    jax.block_until_ready(m)
    t = (time.perf_counter() - t0) / STEPS
    c = cost(train_step.lower(state, batch).compile())
    log(f"full step   : {t*1e3:7.1f} ms  flops={c[0]:.3e} bytes={c[1]:.3e}")
    log(f"roofline: t_mxu(full)={c[0]/197e12*1e3:.1f} ms  "
        f"t_hbm(full)={c[1]/8.1e11*1e3:.1f} ms")
    log(f"imgs/s at full: {BATCH/t:.1f}")


if __name__ == "__main__":
    main()
