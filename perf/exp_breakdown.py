"""Perf experiment: where does the ResNet-50 step time go?

Device tracing is unavailable on the axon relay (jax.profiler.start_trace
hangs before returning — see PERF.md), so this decomposes the step cost by
compiling and timing nested sub-programs:

  fwd            : inference forward (train=False)
  fwd_train      : forward with batch-stat mutation
  grad           : value_and_grad (fwd+bwd), no optimizer
  full           : the real train step (grad + pmean-less update)

and prints XLA cost analysis (flops / bytes accessed) for each, which gives
an analytic roofline: t_mxu = flops / 197e12, t_hbm = bytes / 8.1e11 (v5e).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

jax.config.update("jax_compilation_cache_dir", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".xla_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from tpuframe import models
from tpuframe.models import losses
from tpuframe.parallel import step as step_lib

BATCH = int(os.environ.get("B", "512"))
STEPS = int(os.environ.get("N", "8"))


def log(m):
    print(f"[exp] {m}", file=sys.stderr, flush=True)


def time_fn(fn, *args, steps=STEPS):
    """Time `fn` with async chained dispatch + one final fetch."""
    out = fn(*args)
    jax.block_until_ready(out)  # warmup/compile
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def cost(compiled):
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return ca.get("flops", 0), ca.get("bytes accessed", 0)
    except Exception:
        return 0, 0


def main():
    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0.5, 0.25, size=(BATCH, 224, 224, 3)),
                    jnp.bfloat16)
    y = jnp.asarray(rng.integers(0, 1000, size=(BATCH,)), jnp.int32)
    variables = model.init(jax.random.key(0), x[:2])
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
    params, bstats = variables["params"], variables["batch_stats"]

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    state = step_lib.TrainState.create(
        params, tx, model_state={"batch_stats": bstats})
    train_step = step_lib.make_train_step(loss_fn, tx, None, donate=False)
    batch = {"image": x, "label": y}

    # -- fwd (inference) --
    fwd = jax.jit(lambda p, s, im: model.apply(
        {"params": p, **s}, im, train=False))
    log("timing fwd(infer)...")
    t = time_fn(fwd, params, {"batch_stats": bstats}, x)
    log("cost-analysis fwd(infer)...")
    c = cost(fwd.lower(params, {"batch_stats": bstats}, x).compile())
    log(f"fwd(infer)  : {t*1e3:7.1f} ms  flops={c[0]:.3e} bytes={c[1]:.3e}")

    # -- fwd train (batch stats) --
    fwd_t = jax.jit(lambda p, s, im: model.apply(
        {"params": p, **s}, im, train=True, mutable=["batch_stats"]))
    log("timing fwd(train)...")
    t = time_fn(fwd_t, params, {"batch_stats": bstats}, x)
    log("cost-analysis fwd(train)...")
    c = cost(fwd_t.lower(params, {"batch_stats": bstats}, x).compile())
    log(f"fwd(train)  : {t*1e3:7.1f} ms  flops={c[0]:.3e} bytes={c[1]:.3e}")

    # -- grad --
    def just_grad(p, s, b, r):
        return jax.value_and_grad(loss_fn, has_aux=True)(p, s, b, r)
    gr = jax.jit(just_grad)
    r = jax.random.key(1)
    log("timing grad...")
    t = time_fn(gr, params, {"batch_stats": bstats}, batch, r)
    log("cost-analysis grad...")
    c = cost(gr.lower(params, {"batch_stats": bstats}, batch, r).compile())
    log(f"grad(f+b)   : {t*1e3:7.1f} ms  flops={c[0]:.3e} bytes={c[1]:.3e}")

    # -- full step --
    log("timing full step...")
    new, m = train_step(state, batch)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    cur = state
    for _ in range(STEPS):
        cur, m = train_step(cur, batch)
    jax.block_until_ready(m)
    t = (time.perf_counter() - t0) / STEPS
    c = cost(train_step.lower(state, batch).compile())
    log(f"full step   : {t*1e3:7.1f} ms  flops={c[0]:.3e} bytes={c[1]:.3e}")
    log(f"roofline: t_mxu(full)={c[0]/197e12*1e3:.1f} ms  "
        f"t_hbm(full)={c[1]/8.1e11*1e3:.1f} ms")
    log(f"imgs/s at full: {BATCH/t:.1f}")


if __name__ == "__main__":
    main()
