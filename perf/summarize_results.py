"""Summarize perf/results/* into BASELINE.md-ready markdown.

Pure host-side (no jax).  Run anytime; prints only what exists, each
row stamped with its file's mtime so stale artifacts (e.g. a round-3
fa_tpu_tests.out next to a fresh fa_tpu_tests2.out) are tell-apart-able
at a glance.  The point is to turn a narrow chip window into committed
BASELINE rows fast instead of hand-formatting.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

RES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


def mtime(path) -> str:
    return time.strftime("%m-%d %H:%M", time.gmtime(os.path.getmtime(path)))


def last_json_line(path):
    try:
        for line in reversed(open(path).read().strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
    except (OSError, json.JSONDecodeError):
        pass
    return None


def bench_rows():
    rows = []
    for f in sorted(glob.glob(os.path.join(RES, "bench_*.out"))):
        rec = last_json_line(f)
        if not rec or "value" not in rec:
            continue
        name = os.path.basename(f)[len("bench_"):-len(".out")]
        flag = " (DEGRADED)" if rec.get("degraded") else ""
        mfu = f", mfu {rec['mfu']:.1%}" if "mfu" in rec else ""
        rows.append(f"| {name} | {rec['value']}{flag} | "
                    f"{rec.get('unit', '')}{mfu} | {mtime(f)} | "
                    f"perf/results/{os.path.basename(f)} |")
    if rows:
        print("\n### bench.py (ResNet-50 img/s/chip)\n")
        print("| run | value | unit | written (UTC) | source |")
        print("|---|---|---|---|---|")
        print("\n".join(rows))


def tf_rows():
    for f in sorted(glob.glob(os.path.join(RES, "tf_*.out"))):
        try:
            rows = json.loads(open(f).read())
        except (OSError, json.JSONDecodeError):
            continue
        print(f"\n### {os.path.basename(f)} (written {mtime(f)} UTC)\n")
        print("| model | batch | seq | ms/step | tokens/s |")
        print("|---|---|---|---|---|")
        for r in rows:
            print(f"| {r.get('model')} | {r.get('batch')} | {r.get('seq')} "
                  f"| {r.get('ms_per_step')} | {r.get('tokens_per_s')} |")


def pytest_outcomes():
    for f in sorted(glob.glob(os.path.join(RES, "fa_tpu_tests*.out"))):
        try:
            txt = open(f).read()
        except OSError:
            continue
        m = re.search(r"=+ (.*(?:passed|failed|error).*?) =+\s*$", txt,
                      re.M)
        if m:
            print(f"\n### {os.path.basename(f)} (written {mtime(f)} UTC): "
                  f"{m.group(1)}")
        for line in re.findall(r"^(FAILED .*)$", txt, re.M):
            print(f"  - {line}")


def json_files():
    for name in ("conv_summary.json", "autotune_report.json"):
        path = os.path.join(RES, name)
        if os.path.exists(path):
            print(f"\n### {name}\n```json")
            print(open(path).read().strip()[:2000])
            print("```")


def sweeps():
    rows = []
    for f in sorted(glob.glob(os.path.join(RES, "fa_sweep_*.out"))):
        name = os.path.basename(f)[len("fa_sweep_"):-len(".out")]
        try:
            txt = open(f).read()
        except OSError:
            continue
        for line in txt.strip().splitlines():
            if line.startswith("{") or "tokens/s" in line or "ms" in line:
                rows.append(f"| {name} | `{line.strip()[:100]}` |")
    if rows:
        print("\n### FA block sweep (raw lines)\n")
        print("| blocks | line |")
        print("|---|---|")
        print("\n".join(rows))


def offline_ab_rows():
    """The offline AOT evidence (PERF.md §7-§9): one table, latest row
    per tag."""
    path = os.path.join(RES, "offline_ab.jsonl")
    if not os.path.exists(path):
        return
    # Supersession rule lives in _ab_rows (latest line per tag wins;
    # pinned by tests/test_offline_ab_parser.py).
    from _ab_rows import load_rows, superseded_count

    rows = load_rows(path)
    if not rows:
        return
    dropped = superseded_count(open(path).read().strip().splitlines())
    print(f"\n### offline AOT A/Bs ({mtime(path)}; latest row per tag, "
          f"{dropped} superseded row(s) hidden)\n")
    print("| tag | GB/dev | TFLOP/dev | temp GB | resident GB | note |")
    print("|---|---|---|---|---|---|")
    for r in rows:
        tag = r.get("tag", "?")
        if "compile_error" in r:
            print(f"| {tag} | — | — | — | — | "
                  f"ERROR: {r['compile_error'][:60]} |")
            continue
        gb = r.get("gb_per_dev", r.get("gb", ""))
        fl = r.get("flops_per_dev", r.get("flops", 0)) / 1e12
        print(f"| {tag} | {gb} | {fl:.2f} | "
              f"{r.get('temp_gb_per_dev', r.get('temp_gb', ''))} | "
              f"{r.get('resident_gb_per_dev', '')} | "
              f"{'ar=' + str(r['allreduce_payload_mb']) + 'MB' if 'allreduce_payload_mb' in r else ''} |")


def main():
    print("# perf/results summary (generated by perf/summarize_results.py)")
    bench_rows()
    tf_rows()
    pytest_outcomes()
    sweeps()
    offline_ab_rows()
    json_files()
    census = os.path.join(RES, "hlo_dump.err")
    if os.path.exists(census) and os.path.getsize(census):
        print("\n### hlo_dump (byte census) log tail\n```")
        print("\n".join(open(census).read().strip().splitlines()[-30:]))
        print("```")


if __name__ == "__main__":
    sys.exit(main())
