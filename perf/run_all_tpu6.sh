#!/bin/bash
# Round-4 TPU queue #6 — the SCHEDULING levers (PERF.md §7 finding 4a).
#
# The offline census closed the bytes question: 143.5 GB/step is
# structural, layout is already good, folded-BN is a null, remat is
# negative.  What remains between measured 218 ms and the 177 ms HBM
# roofline is a 23% SCHEDULING gap — prefetch depth, compute/DMA
# overlap.  These are runtime A/Bs that only the chip can measure:
#   1. latency-hiding scheduler on/off at the bench optimum (batch 256)
#   2. scoped-vmem limit sweep (VMEM reserved for the scheduler's
#      prefetch buffers; too little starves overlap, too much starves
#      fusion scratch)
#   3. best-combo confirmation run at 512 for the roofline comparison
# Run AFTER queues 4b/5 (chip claim + one-client rules via claim.sh).
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_all6.log
echo "=== run_all_tpu6 $(date -u +%FT%TZ) ===" >> "$LOG"
. perf/claim.sh

note() { echo "[run_all6 $(date -u +%T)] $*" | tee -a "$LOG"; }

claim_wait_for_others | tee -a "$LOG"
note "phase 0: chip claim"
if ! claim_chip 96 "$LOG"; then
  note "claim FAILED; giving up"
  exit 1
fi

run() { queue_run "$@"; }

# Flags ride TPUFRAME_XLA_OPTS -> jit compiler_options: XLA_FLAGS would
# crash the local parser (TPU flags unknown to the host XLA) and
# LIBTPU_INIT_ARGS does not cross the relay's remote-compile boundary;
# compiler_options is part of the compile request itself (verified
# accepted by the v5e compiler via the offline topology).

# 1. latency-hiding scheduler A/B at batch 256.
TPUFRAME_BENCH_BATCH=256 \
    TPUFRAME_XLA_OPTS="xla_tpu_enable_latency_hiding_scheduler=true" \
    run bench_b256_lhs 1200 python bench.py

# 2. scoped-vmem sweep (default is compiler-chosen; KiB per core).
for kib in 16384 32768 65536; do
  TPUFRAME_BENCH_BATCH=256 \
      TPUFRAME_XLA_OPTS="xla_tpu_scoped_vmem_limit_kib=$kib" \
      run bench_b256_vmem$kib 1200 python bench.py
done

# 3. combine the winners (re-edit after reading 1-2 if needed) and
#    confirm at 512 for the roofline table.
TPUFRAME_BENCH_BATCH=512 \
    TPUFRAME_XLA_OPTS="xla_tpu_enable_latency_hiding_scheduler=true" \
    run bench_b512_lhs 1200 python bench.py

note "queue 6 complete"
