#!/bin/bash
# Watches queue 2; when its runner exits (success or give-up), runs queue 3.
# Queue 3's own patient claim loop handles a still-wedged relay.
set -u
cd "$(dirname "$0")/.."
LOG=perf/results/chain.log
echo "=== chain watcher $(date -u +%FT%TZ) ===" >> "$LOG"
while pgrep -f "run_all_tpu2.sh" > /dev/null; do
  sleep 60
done
echo "[chain $(date -u +%T)] queue 2 runner gone; starting queue 3" >> "$LOG"
bash perf/run_all_tpu3.sh >> "$LOG" 2>&1
echo "[chain $(date -u +%T)] queue 3 runner exited" >> "$LOG"
