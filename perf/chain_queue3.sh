#!/bin/bash
# Watches queue 2 (PID-anchored); when its runner exits, runs queue 3.
# Queue 3's own patient claim loop handles a still-wedged relay.
set -u
cd "$(dirname "$0")/.."
LOG=perf/results/chain.log
echo "=== chain watcher $(date -u +%FT%TZ) ===" >> "$LOG"
# Resolve the runner PID up front; allow up to 10 min for it to appear so a
# watcher started first cannot racily conclude queue 2 already finished.
pid=""
for _ in $(seq 1 20); do
  pid=$(pgrep -of "bash .*run_all_tpu2.sh" || true)
  [ -n "$pid" ] && break
  sleep 30
done
if [ -n "$pid" ]; then
  echo "[chain $(date -u +%T)] watching queue-2 runner pid=$pid" >> "$LOG"
  while kill -0 "$pid" 2>/dev/null; do sleep 60; done
else
  echo "[chain $(date -u +%T)] no queue-2 runner found; proceeding" >> "$LOG"
fi
echo "[chain $(date -u +%T)] queue 2 done; starting queue 3" >> "$LOG"
bash perf/run_all_tpu3.sh >> "$LOG" 2>&1
echo "[chain $(date -u +%T)] queue 3 runner exited" >> "$LOG"
