"""Offline AOT A/Bs on the compile-only v5e topology (PERF.md §7).

Extends `exp_hlo_offline.py`'s discovery to the transformer workloads and
the multi-chip DP program — compiler-measured evidence (bytes accessed,
flops, temp memory, collective payloads) with the relay out of the loop:

  lm_xent  — TransformerLM 124M b=8 s=2048: dense head+loss vs the
             chunked fused softmax-xent (tpuframe/ops/fused_xent.py).
             The fused op's claim is that the [B,S,V] logits never land
             in HBM; `bytes accessed` is the direct check.
  lm_8k    — b=2 s=8192: XLA full attention vs the pallas flash kernel.
             On-chip the XLA variant FAILS TO COMPILE (S^2 scores at
             seq 8k, BASELINE.md round 3); AOT memory_analysis shows the
             footprint both ways without needing 16 GB of real HBM.
  dp32     — ResNet-50 DP train step over 32 compile-only v5e devices
             (topology 4x8): the all-reduce payloads of the ACTUAL TPU
             lowering, cross-checking tests/test_scaling32.py's
             CPU-mesh HLO and the scaling projection's traffic input.
  bert_b256— BERT-base classification step at b=256 s=128: the
             queue-4 on-chip A/B's byte/temp picture, offline.
  remat    — the donated ResNet-50 b=512 train step under tpuframe.mem
             remat policies (REMAT_POLICIES=comma,list overrides the
             default none,dots,per_block set).  Rows carry a ``policy``
             column; the _ab_rows key is (tag, policy), so every policy
             row survives next to the ``none`` baseline.

Usage:  python perf/exp_offline_ab.py [lm_xent|lm_8k|dp32|bert_b256|remat|all]
Appends JSON lines to perf/results/offline_ab.jsonl.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _common import (ensure_cpu_backend, hold_aot_lock,  # noqa: E402
                     to_shape_structs)

ensure_cpu_backend()
hold_aot_lock()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results",
                   "offline_ab.jsonl")


def log(m):
    print(f"[offline-ab] {m}", file=sys.stderr, flush=True)


def record(row):
    row["source"] = "offline AOT v5e topology compile"
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row), flush=True)


def _topo_mesh(shape="v5e:2x2", n=1, axes=("data",)):
    topo = topologies.get_topology_desc(shape, platform="tpu")
    devs = np.array(topo.devices[:n]).reshape([n] if len(axes) == 1 else None)
    return Mesh(devs, axes)


def _analyze(compiled, tag, extra=None):
    ca = compiled.cost_analysis() or {}
    row = {"tag": tag, "flops": ca.get("flops", 0.0),
           "bytes": ca.get("bytes accessed", 0.0),
           "gb": round(ca.get("bytes accessed", 0.0) / 1e9, 2)}
    try:
        ma = compiled.memory_analysis()
        row["temp_gb"] = round(ma.temp_size_in_bytes / 1e9, 2)
        row["arg_gb"] = round(ma.argument_size_in_bytes / 1e9, 2)
    except Exception as e:  # noqa: BLE001
        row["memory_analysis_error"] = str(e)[:120]
    if extra:
        row.update(extra)
    return row


def _lm_step(seq, batch_size, attn_impl, fused, repl):
    from tpuframe.models import losses
    from tpuframe.models.transformer_lm import LMConfig, TransformerLM
    from tpuframe.parallel import step as step_lib

    cfg = LMConfig(vocab_size=32000, hidden_size=768, num_layers=12,
                   num_heads=12, intermediate_size=3072, max_seq=seq,
                   dtype="bfloat16", attn_impl=attn_impl, remat=True)
    model = TransformerLM(cfg)
    ids = jax.ShapeDtypeStruct((batch_size, seq), jnp.int32, sharding=repl)
    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, seq), jnp.int32)),
        jax.random.key(0))
    tx = optax.adamw(1e-4)

    if fused:
        from tpuframe.ops import fused_xent as fx

        def loss_fn(params, model_state, b, rng):
            hidden = model.apply({"params": params}, b["input_ids"],
                                 train=True, rngs={"dropout": rng},
                                 hidden_only=True)
            w = params["lm_head"]["kernel"]
            loss = jnp.mean(fx.fused_softmax_xent(hidden, w, b["labels"]))
            return loss, ({}, {})
    else:
        def loss_fn(params, model_state, b, rng):
            logits = model.apply({"params": params}, b["input_ids"],
                                 train=True, rngs={"dropout": rng})
            return losses.softmax_cross_entropy(logits, b["labels"]), ({}, {})

    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(v["params"], tx), variables)
    state = to_shape_structs(state, repl)
    step = step_lib.make_train_step(loss_fn, tx, None, donate=False)
    batch = {"input_ids": ids, "labels": ids}
    return step, state, batch


def lm_xent():
    mesh = _topo_mesh(n=1)
    repl = NamedSharding(mesh, P())
    # Third variant is the PERF.md §8 headline row: flash attention +
    # fused head — the byte-minimal LM step.
    for attn, fused, tag in (("xla", False, "lm_2k_dense_xent"),
                             ("xla", True, "lm_2k_fused_xent"),
                             ("pallas", True, "lm_2k_pallas_fusedxent")):
        log(f"compiling {tag}...")
        step, state, batch = _lm_step(2048, 8, attn, fused, repl)
        compiled = jax.jit(step).lower(state, batch).compile()
        record(_analyze(compiled, tag,
                        {"batch": 8, "seq": 2048, "attn": attn}))


def lm_8k():
    mesh = _topo_mesh(n=1)
    repl = NamedSharding(mesh, P())
    for attn in ("xla", "pallas"):
        tag = f"lm_8k_{attn}_attn"
        log(f"compiling {tag}...")
        try:
            step, state, batch = _lm_step(8192, 2, attn, True, repl)
            compiled = jax.jit(step).lower(state, batch).compile()
            record(_analyze(compiled, tag, {"batch": 2, "seq": 8192}))
        except Exception as e:  # noqa: BLE001
            record({"tag": tag, "batch": 2, "seq": 8192,
                    "compile_error": str(e)[:300]})


def bert_b256():
    """BERT-base classification step at b=256 s=128 — the queue-4 on-chip
    A/B's byte/residency picture, available offline.  BERT_LARGE=1
    compiles the 24-layer/1024-hidden large variant at b=128 instead
    (model-scale headroom evidence: the reference genre's next size up)."""
    from tpuframe.models import bert as bert_lib
    from tpuframe.models import losses
    from tpuframe.parallel import step as step_lib

    mesh = _topo_mesh(n=1)
    repl = NamedSharding(mesh, P())
    large = os.environ.get("BERT_LARGE") == "1"
    if large:
        cfg = bert_lib.BertConfig(dtype="bfloat16", hidden_size=1024,
                                  num_layers=24, num_heads=16,
                                  intermediate_size=4096)
        B, S = 128, 128
    else:
        cfg = bert_lib.BertConfig(dtype="bfloat16")
        B, S = 256, 128
    model = bert_lib.BertForSequenceClassification(cfg)
    ids = jax.ShapeDtypeStruct((B, S), jnp.int32, sharding=repl)
    lab = jax.ShapeDtypeStruct((B,), jnp.int32, sharding=repl)
    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, S), jnp.int32),
                             jnp.ones((1, S), jnp.int32),
                             jnp.zeros((1, S), jnp.int32)),
        jax.random.key(0))
    tx = optax.adamw(2e-5)

    def loss_fn(params, model_state, b, rng):
        logits = model.apply({"params": params}, b["input_ids"],
                             b["attention_mask"], b["token_type_ids"],
                             train=True, rngs={"dropout": rng})
        return losses.softmax_cross_entropy(logits, b["label"]), ({}, {})

    state = to_shape_structs(jax.eval_shape(
        lambda v: step_lib.TrainState.create(v["params"], tx), variables),
        repl)
    step = step_lib.make_train_step(loss_fn, tx, None, donate=False)
    batch = {"input_ids": ids, "attention_mask": ids,
             "token_type_ids": ids, "label": lab}
    tag = "bert_large_b128" if large else "bert_b256"
    log(f"compiling {tag} s=128...")
    compiled = jax.jit(step).lower(state, batch).compile()
    record(_analyze(compiled, tag, {"batch": B, "seq": S}))


def dp32():
    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import step as step_lib

    from tpuframe.parallel import mesh as mesh_lib

    # TOPO=v4:2x2x4 compiles the same program against the v4-32 north
    # star (16 chips x 2 TensorCores = 32 devices, BASELINE.json:5).
    topo = topologies.get_topology_desc(
        os.environ.get("TOPO", "v5e:4x8"), platform="tpu")
    n = len(topo.devices)
    # The framework mesh (all six axes; only data sized) so the step's
    # default batch partition P(('data','fsdp')) resolves.
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=n),
                              devices=list(topo.devices))
    repl = NamedSharding(mesh, P())
    dsh = NamedSharding(mesh, mesh_lib.batch_spec())
    log(f"dp32: {n} compile-only devices")

    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((2, 224, 224, 3), jnp.bfloat16)),
        jax.random.key(0))
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"])
        return loss, (dict(mutated), {})

    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(
            v["params"], tx, model_state={"batch_stats": v["batch_stats"]}),
        variables)
    state = to_shape_structs(state, repl)
    # Per-chip batch 8 keeps the compile tractable; collective payloads
    # depend on the GRADIENT tree, not the batch size.
    batch = {"image": jax.ShapeDtypeStruct((8 * n, 224, 224, 3),
                                           jnp.bfloat16, sharding=dsh),
             "label": jax.ShapeDtypeStruct((8 * n,), jnp.int32,
                                           sharding=dsh)}
    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False)
    log("compiling the 32-device DP step (this is the big one)...")
    compiled = jax.jit(step).lower(state, batch).compile()
    txt = compiled.as_text()

    # Sum all-reduce payloads from the TPU lowering (shared parser —
    # pinned by tests/test_offline_ab_parser.py).
    from _hlo_parse import allreduce_payload

    payload, ops = allreduce_payload(txt)
    from _common import topo_tag_suffix

    record(_analyze(compiled, "resnet50_dp32" + topo_tag_suffix(
        os.environ.get("TOPO", "v5e:4x8"), "v5e:4x8"), {
        "devices": n, "allreduce_ops": ops,
        "allreduce_payload_mb": round(sum(payload.values()) / 1e6, 2),
        "payload_bf16_mb": round(payload["bf16"] / 1e6, 2),
        "payload_f32_mb": round(payload["f32"] / 1e6, 2),
        "grad_tree_f32_mb": 102.4}))


def remat_ab():
    """Donated ResNet-50 b=512 train step per tpuframe.mem remat policy —
    the same program tune's ``remat_sweep`` scores, as A/B rows (one
    ``policy`` column per line; ~4 min compile each)."""
    from tpuframe.tune import search as tune_search

    topo = topologies.get_topology_desc("v5e:2x2", platform="tpu")
    raw = os.environ.get("REMAT_POLICIES", "none,dots,per_block")
    policies = tuple(p.strip() for p in raw.split(",") if p.strip())
    for pol in policies:
        log(f"compiling resnet50_remat_b512 policy={pol}...")
        try:
            compiled, _ = tune_search._remat_step_compile(
                topo.devices, 512, pol)
            record(_analyze(compiled, "resnet50_remat_b512",
                            {"batch": 512, "policy": pol}))
        except Exception as e:  # noqa: BLE001 — e.g. `full` OOMs the v5e
            record({"tag": "resnet50_remat_b512", "batch": 512,
                    "policy": pol, "compile_error": str(e)[:300]})


def show():
    """Print the SURVIVING rows (supersession rule in _ab_rows: latest
    line per tag wins — §11 regenerations hide the round-4 rows)."""
    from _ab_rows import load_rows, superseded_count

    rows = load_rows(OUT)
    dropped = superseded_count(open(OUT).read().strip().splitlines())
    log(f"{len(rows)} surviving row(s), {dropped} superseded")
    for row in rows:
        print(json.dumps(row))


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    steps = {"lm_xent": lm_xent, "lm_8k": lm_8k, "dp32": dp32,
             "bert_b256": bert_b256, "remat": remat_ab}
    if which == "show":
        return show()
    if which == "all":
        for name, fn in steps.items():
            log(f"=== {name} ===")
            fn()
    else:
        steps[which]()


if __name__ == "__main__":
    main()
