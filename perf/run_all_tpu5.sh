#!/bin/bash
# Round-4 TPU queue #5 (chained from run_all_tpu4.sh's extension hook):
# close VERDICT stretch #8 — pallas flash attention must be <= XLA at
# seq 2k, not 5% slower.  The kernel changes this round (dimension
# semantics declared parallel, causal interior blocks skip the tri-mask
# VPU chain, env-tunable block sizes) shift the landscape; this queue
# measures it:
#   1. block-size sweep at 2k/4k, fwd + fwd/bwd (block sizes are read
#      from env at import, so each point is its own process)
#   2. LM train-step pallas-vs-xla A/B with the tuned kernel
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_all5.log
echo "=== run_all_tpu5 $(date -u +%FT%TZ) ===" >> "$LOG"
. perf/claim.sh

note() { echo "[run_all5 $(date -u +%T)] $*" | tee -a "$LOG"; }

claim_wait_for_others | tee -a "$LOG"
note "phase 0: chip claim (short loop; usually chained from a hot queue 4)"
if ! claim_chip 20 "$LOG"; then
  note "phase 0 FAILED; giving up"
  exit 1
fi

run() { queue_run "$@"; }  # shared runner: perf/claim.sh (outage re-claim + retry)

# 1. Block-size sweep.  (128,128) is the round-3 baseline point but with
# this round's kernel scheduling changes — the direct A/B for them.
for blocks in 128x128 128x256 128x512 256x256 256x512 512x512; do
  bq=${blocks%x*} bk=${blocks#*x}
  SEQS=2048,4096 TPUFRAME_FA_BLOCK_Q=$bq TPUFRAME_FA_BLOCK_K=$bk \
      run fa_sweep_$blocks 1800 python perf/bench_attention.py
done

# 2. Train-step A/B at the standard LM shape with the (default-block)
# optimized kernel — the number VERDICT #8 compares: pallas vs xla ms/step.
MODEL=lm run tf_lm_2k_opt 2400 python perf/bench_transformer.py

# 3. ResNet remat A/B: on a bandwidth-bound step (81% of the HBM roofline,
# MXU 29% busy) recomputing intra-block activations with idle MXU cycles
# may beat storing+reloading them.
TPUFRAME_BENCH_BATCH=256 TPUFRAME_BENCH_REMAT=1 \
    run bench_b256_remat 1200 python bench.py
# If both independently help at 256, the byte savings should stack.
TPUFRAME_BENCH_BATCH=256 TPUFRAME_BENCH_REMAT=1 \
    TPUFRAME_BENCH_STEM=space_to_depth \
    run bench_b256_remat_s2d 1200 python bench.py

note "queue 5 complete"
