#!/bin/bash
# Phase-2-only repro for the pod resume hang: restore the 4-proc-written
# checkpoint on a 2-proc cluster.  Unbuffered, faulthandler armed, SIGABRT
# on timeout so every rank dumps thread stacks.
set -u
cd "$(dirname "$0")/.."
D=${D:-/tmp/podtest}
PORT=${PORT:-24561}
TMO=${TMO:-240}
for pid in 0 1; do
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=2" \
  TPUFRAME_COORDINATOR=127.0.0.1:$PORT \
  TPUFRAME_NUM_PROCESSES=2 TPUFRAME_PROCESS_ID=$pid \
  timeout -s ABRT "$TMO" python -u -X faulthandler -m tpuframe.train \
    --config imagenet_resnet50_pod \
    --set total_steps=8 --set ckpt_every=4 --set global_batch=32 \
    --set log_every=4 --set eval_every=1000 --set warmup_steps=2 \
    --set "compute_dtype='float32'" \
    --set "dataset_kwargs={'image_size': 32, 'synthetic_size': 64, 'num_classes': 100}" \
    --set "model_kwargs={'cifar_stem': True, 'num_classes': 100}" \
    --ckpt-dir "$D/ck" > "$D/dbg.r$pid.out" 2> "$D/dbg.r$pid.err" &
done
wait
echo "=== r0 out ==="; tail -8 "$D/dbg.r0.out"
echo "=== r0 err ==="; tail -40 "$D/dbg.r0.err"
echo "=== r1 err ==="; tail -40 "$D/dbg.r1.err"
