"""Offline capacity audit: AOT-compile every beyond-DP flagship config at
its REAL shape against compile-only v5e devices.

Motivation (PERF.md §9): the 32k ring-attention step OOM'd at real scale
while every CI test passed at toy shapes.  This audit closes that class
for the remaining parallelism strategies — each entry compiles the full
production-sized step on an 8-device v5e topology and records bytes /
temp memory / collectives, or an honest compile_error row.

  lm_long_exact   — the lm_long config verbatim: dp1 x sp8, b=8,
                    seq 32768, ring attention + fused xent.
  lm_pp_realistic — ScanBlockLM 124M-class over pipe=4 x data=2,
                    b=8 x seq 2048 (GPipe microbatching).
  lm_moe_realistic— MoE TransformerLM, 8 experts over ep=4 x data=2,
                    b=8 x seq 2048.

Usage: python perf/exp_capacity_audit.py [name|all]
Appends JSON lines to perf/results/offline_ab.jsonl.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _common import (ensure_cpu_backend, hold_aot_lock,  # noqa: E402
                     to_shape_structs)

ensure_cpu_backend()
hold_aot_lock()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results",
                   "offline_ab.jsonl")

# 8-device compile-only topology: "v5e:2x4" (16 GB HBM) by default;
# TOPO=v4:2x2x2 re-audits every entry against the v4 family (32 GB HBM,
# the BASELINE.json:5 north-star hardware) — VERDICT r4 #5.
TOPO = os.environ.get("TOPO", "v5e:2x4")


def log(m):
    print(f"[capacity] {m}", file=sys.stderr, flush=True)


def _tag(base):
    from _common import topo_tag_suffix

    return base + topo_tag_suffix(TOPO, "v5e:2x4")


def record(row):
    row["source"] = f"offline AOT {TOPO} topology compile"
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row), flush=True)


def _summarize(c, tag, extra):
    txt = c.as_text()
    ca = c.cost_analysis() or {}
    ma = c.memory_analysis()
    # Residency = temp + arguments (+ undonated outputs): temp alone
    # understates a config at the capacity edge (review catch — the
    # replicated params/moments are argument memory, ~GBs at dp1).
    arg = ma.argument_size_in_bytes
    outb = ma.output_size_in_bytes
    alias = getattr(ma, "alias_size_in_bytes", 0)
    row = {"tag": tag,
           "bytes": ca.get("bytes accessed", 0.0),
           "gb_per_dev": round(ca.get("bytes accessed", 0.0) / 1e9, 2),
           "flops_per_dev": ca.get("flops", 0.0),
           "temp_gb_per_dev": round(ma.temp_size_in_bytes / 1e9, 2),
           "arg_gb_per_dev": round(arg / 1e9, 2),
           "out_gb_per_dev": round(outb / 1e9, 2),
           "alias_gb_per_dev": round(alias / 1e9, 2),
           "resident_gb_per_dev": round(
               (ma.temp_size_in_bytes + arg + outb - alias) / 1e9, 2),
           "collective_permutes": (txt.count("collective-permute(")
                                   + txt.count("collective-permute-start(")),
           "all_to_alls": txt.count(" all-to-all("),
           "all_reduces": (txt.count(" all-reduce(")
                           + txt.count(" all-reduce-start("))}
    row.update(extra)
    return row


def _lm_long(tag, data, sp, batch, seq_mode="ring", attn_impl="xla"):
    """Shared 32k sequence-parallel builder (dp x sp, ring or ulysses)."""
    from tpuframe import models
    from tpuframe.ops import fused_xent as fx
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib

    topo = topologies.get_topology_desc(TOPO, platform="tpu")
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=data, seq=sp),
                              devices=list(topo.devices))
    SEQ = 32768
    model = models.get_model(
        "transformer-lm", hidden_size=768, num_layers=12, num_heads=12,
        intermediate_size=3072, vocab_size=32000, max_seq=SEQ,
        seq_mode=seq_mode, attn_impl=attn_impl, remat=True,
        dtype="bfloat16")
    repl = NamedSharding(mesh, P())
    part = P(mesh_lib.BATCH_AXES, "seq")
    ids = jax.ShapeDtypeStruct((batch, SEQ), jnp.int32,
                               sharding=NamedSharding(mesh, part))
    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, SEQ), jnp.int32)),
        jax.random.key(0))
    tx = optax.adamw(3e-4)

    def loss_fn(params, model_state, b, rng):
        hidden = model.apply({"params": params}, b["input_ids"], train=True,
                             rngs={"dropout": rng}, hidden_only=True)
        loss = jnp.mean(fx.fused_softmax_xent(
            hidden, params["lm_head"]["kernel"], b["labels"]))
        return loss, ({}, {})

    state = to_shape_structs(jax.eval_shape(
        lambda v: step_lib.TrainState.create(v["params"], tx), variables),
        repl)
    step = step_lib.make_train_step(
        loss_fn, tx, mesh, donate=True, batch_partition=part,
        reduce_axes=(*mesh_lib.BATCH_AXES, "seq"))
    log(f"compiling {tag} (dp{data} x sp{sp}, b={batch}, 32k)...")
    # step is already jitted WITH donation; an outer jax.jit would wrap
    # it in a donation-less jit and erase the aliasing from the audit.
    c = step.lower(state, {"input_ids": ids, "labels": ids}).compile()
    record(_summarize(c, _tag(tag), {"devices": 8, "seq": SEQ, "batch": batch}))


def lm_long_exact():
    """lm_long verbatim: dp1 x sp8, global batch 8, seq 32768."""
    _lm_long("lm_long_exact_dp1sp8", 1, 8, 8)


def lm_32k_dp2sp4():
    """The PERF.md section-9 headline variant: dp2 x sp4, b=2, 32k."""
    _lm_long("lm_32k_sp_ring_dp2sp4", 2, 4, 2)


def lm_32k_ring_pallas():
    """Ring attention with FLASH stages (round-5: flash_mha_lse + the
    logsumexp stage merge) at the same dp2 x sp4 32k shape — the direct
    A/B against both the xla-stage ring (round-4 row: the >=2x byte
    penalty) and Ulysses+flash.  Ring is the documented fallback when
    heads don't divide sp, so its stages must not be byte-penalized."""
    _lm_long("lm_32k_sp_ring_pallas_dp2sp4", 2, 4, 2,
             seq_mode="ring", attn_impl="pallas")


def lm_long_exact_pallas():
    """lm_long verbatim (dp1 x sp8, b=8, 32k) with flash ring stages."""
    _lm_long("lm_long_exact_pallas_dp1sp8", 1, 8, 8,
             seq_mode="ring", attn_impl="pallas")


def lm_32k_ulysses():
    """Ulysses (all-to-all head-resharding) at the same 32k shape —
    the other first-class SP mode, at real scale.  The inner attention
    MUST be the flash kernel: after resharding, each device holds the
    FULL 32k sequence on heads/sp heads, and XLA attention's S^2 scores
    OOM (20.3 GB vs 15.75 — the audit's xla-inner row records exactly
    that).  Pairing rule documented in PERF.md section 9."""
    _lm_long("lm_32k_sp_ulysses_pallas_dp2sp4", 2, 4, 2,
             seq_mode="ulysses", attn_impl="pallas")


def lm_tp_realistic():
    """Megatron-style tensor parallel at real shape: tp4 x dp2, 124M LM,
    b=8 s=2048, sharded state via the fsdp/tp rule tree."""
    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import fsdp as fsdp_lib
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib
    from tpuframe.parallel import tp as tp_lib

    topo = topologies.get_topology_desc(TOPO, platform="tpu")
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, model=4),
                              devices=list(topo.devices))
    model = models.get_model(
        "transformer-lm", hidden_size=768, num_layers=12, num_heads=12,
        intermediate_size=3072, vocab_size=32000, max_seq=2048,
        dtype="bfloat16", remat=True)
    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 2048), jnp.int32)),
        jax.random.key(0))
    tx = optax.adamw(3e-4)

    def loss_fn(params, model_state, b, rng):
        logits = model.apply({"params": params}, b["input_ids"], train=True,
                             rngs={"dropout": rng})
        return losses.softmax_cross_entropy(logits, b["labels"]), ({}, {})

    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(v["params"], tx), variables)
    shardings = fsdp_lib.state_shardings(
        state, mesh, tp_rules=tp_lib.rules_for_model("transformer-lm"))
    state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)
        if hasattr(s, "shape") else s, state, shardings,
        is_leaf=lambda l: isinstance(l, jax.ShapeDtypeStruct))
    dmesh = fsdp_lib.auto_mesh(mesh)
    ids = jax.ShapeDtypeStruct(
        (8, 2048), jnp.int32,
        sharding=NamedSharding(dmesh, mesh_lib.batch_spec()))
    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=True,
                                    state_shardings=shardings)
    log("compiling TP LM (tp4 x data2, b=8 s=2048)...")
    c = step.lower(state, {"input_ids": ids, "labels": ids}).compile()
    record(_summarize(c, _tag("lm_tp_tp4data2"), {
        "devices": 8, "seq": 2048, "batch": 8}))


def lm_pp_realistic():
    """ScanBlockLM over pipe=4 x data=2 at 124M-class size, b=8 s=2048."""
    from tpuframe.models.transformer_lm import LMConfig, ScanBlockLM
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import pp_lm
    from tpuframe.parallel import step as step_lib

    topo = topologies.get_topology_desc(TOPO, platform="tpu")
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, pipe=4),
                              devices=list(topo.devices))
    cfg = LMConfig(vocab_size=32000, hidden_size=768, num_layers=12,
                   num_heads=12, intermediate_size=3072, max_seq=2048,
                   dtype="bfloat16", remat=True, dropout=0.0)
    model = ScanBlockLM(cfg)
    tx = optax.adamw(3e-4)
    abstract = jax.eval_shape(
        lambda k: step_lib.TrainState.create(
            model.init(k, jnp.zeros((1, 2048), jnp.int32))["params"], tx),
        jax.random.key(0))
    specs = pp_lm.state_partition(abstract)
    state = jax.tree.map(
        lambda s, sp: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=NamedSharding(mesh, sp))
        if hasattr(s, "shape") else s, abstract, specs,
        is_leaf=lambda l: isinstance(l, jax.ShapeDtypeStruct))
    factory, _, _ = pp_lm.make_pp_lm_step(model, tx, mesh, n_micro=4)
    step = factory(abstract)
    ids = jax.ShapeDtypeStruct(
        (8, 2048), jnp.int32,
        sharding=NamedSharding(mesh, P(mesh_lib.BATCH_AXES)))
    log("compiling pp LM (pipe4 x data2, 124M-class, b=8 s=2048)...")
    c = step.lower(state, {"input_ids": ids, "labels": ids}).compile()
    record(_summarize(c, _tag("lm_pp_pipe4data2"), {
        "devices": 8, "seq": 2048, "batch": 8}))


def lm_moe_realistic():
    """MoE TransformerLM: 8 experts over ep=4 x data=2, b=8 s=2048."""
    from tpuframe import models
    from tpuframe.models import losses
    from tpuframe.parallel import fsdp as fsdp_lib
    from tpuframe.parallel import mesh as mesh_lib
    from tpuframe.parallel import step as step_lib
    from tpuframe.parallel import tp as tp_lib

    topo = topologies.get_topology_desc(TOPO, platform="tpu")
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, expert=4),
                              devices=list(topo.devices))
    model = models.get_model(
        "transformer-lm", hidden_size=768, num_layers=12, num_heads=12,
        intermediate_size=3072, vocab_size=32000, max_seq=2048,
        dtype="bfloat16", remat=True, moe_experts=8, moe_k=2, moe_every=2)
    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 2048), jnp.int32)),
        jax.random.key(0))
    tx = optax.adamw(3e-4)

    def loss_fn(params, model_state, b, rng):
        logits, sown = model.apply({"params": params}, b["input_ids"],
                                   train=True, rngs={"dropout": rng},
                                   mutable=["aux_loss"])
        loss = losses.softmax_cross_entropy(logits, b["labels"])
        leaves = jax.tree.leaves(sown)
        aux = sum(leaves) / max(len(leaves), 1)
        return loss + 0.01 * aux, ({}, {})

    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(v["params"], tx), variables)
    shardings = fsdp_lib.state_shardings(
        state, mesh, tp_rules=tp_lib.rules_for_model("transformer-lm"))
    state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)
        if hasattr(s, "shape") else s, state, shardings,
        is_leaf=lambda l: isinstance(l, jax.ShapeDtypeStruct))
    dmesh = fsdp_lib.auto_mesh(mesh)
    ids = jax.ShapeDtypeStruct(
        (8, 2048), jnp.int32,
        sharding=NamedSharding(dmesh, mesh_lib.batch_spec()))
    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=True,
                                    state_shardings=shardings)
    log("compiling MoE LM (ep4 x data2, 8 experts, b=8 s=2048)...")
    c = step.lower(state, {"input_ids": ids, "labels": ids}).compile()
    record(_summarize(c, _tag("lm_moe_ep4data2"), {
        "devices": 8, "seq": 2048, "batch": 8, "experts": 8}))


ENTRIES = {
    "lm_long_exact": (lm_long_exact, {
        "tag": "lm_long_exact_dp1sp8", "devices": 8, "seq": 32768,
        "batch": 8}),
    "lm_32k_dp2sp4": (lm_32k_dp2sp4, {
        "tag": "lm_32k_sp_ring_dp2sp4", "devices": 8, "seq": 32768,
        "batch": 2}),
    "lm_32k_ring_pallas": (lm_32k_ring_pallas, {
        "tag": "lm_32k_sp_ring_pallas_dp2sp4", "devices": 8, "seq": 32768,
        "batch": 2}),
    "lm_long_exact_pallas": (lm_long_exact_pallas, {
        "tag": "lm_long_exact_pallas_dp1sp8", "devices": 8, "seq": 32768,
        "batch": 8}),
    "lm_32k_ulysses": (lm_32k_ulysses, {
        "tag": "lm_32k_sp_ulysses_pallas_dp2sp4", "devices": 8,
        "seq": 32768, "batch": 2}),
    "lm_tp_realistic": (lm_tp_realistic, {
        "tag": "lm_tp_tp4data2", "devices": 8, "seq": 2048, "batch": 8}),
    "lm_pp_realistic": (lm_pp_realistic, {
        "tag": "lm_pp_pipe4data2", "devices": 8, "seq": 2048, "batch": 8}),
    "lm_moe_realistic": (lm_moe_realistic, {
        "tag": "lm_moe_ep4data2", "devices": 8, "seq": 2048, "batch": 8,
        "experts": 8}),
}


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    targets = ENTRIES.values() if which == "all" else [ENTRIES[which]]
    for fn, meta in targets:
        try:
            fn()
        except Exception as e:  # noqa: BLE001
            record({**meta, "compile_error": str(e)[:400]})


if __name__ == "__main__":
    main()
