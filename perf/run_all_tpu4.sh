#!/bin/bash
# Round-4 consolidated TPU queue — everything the round-3 relay outage
# blocked, in VERDICT-priority order:
#   1. FA on-chip tests after the f32-tolerance + precision plumbing fix
#      (expect 8/8) + Mosaic precision probe (VERDICT missing #1 / weak #4)
#   2. HLO byte census of the 143.5 GB/step (VERDICT missing #2)
#   3. bench regeneration at all sweep batches under the corrected MFU
#      accounting (VERDICT weak #2) — overwrites the stale "mfu: 0.1489"
#      artifacts with honest chained-async numbers
#   4. convergence + crash/resume proof (VERDICT missing #5)
#   5. honest attention/breakdown timings (queue-2 carryover)
#   6. transformer A/Bs: fused-xent, 8k/32k long context, BERT b256,
#      remat on/off (queue-3 carryover; VERDICT missing #4)
#   7. live autotune demo
# Relay rules (PERF.md §0): ONE client, strictly serial, never kill a
# client mid-claim.  Ends by chaining perf/run_all_tpu5.sh if present
# (extension hook — a running bash script must not be edited in place).
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_all4.log
echo "=== run_all_tpu4 $(date -u +%FT%TZ) ===" >> "$LOG"
. perf/claim.sh

note() { echo "[run_all4 $(date -u +%T)] $*" | tee -a "$LOG"; }

# Phase -1: the one-client rule across queues.
claim_wait_for_others | tee -a "$LOG"

note "phase 0: probing for chip claim (retry loop, up to ~8h)..."
if ! claim_chip 96 "$LOG"; then
  note "phase 0 FAILED — relay wedged for the whole window; giving up"
  exit 1
fi
note "chip claimed — running queue 4"

run() { queue_run "$@"; }  # shared runner: perf/claim.sh (outage re-claim + retry)

# --- 1. flash-attention proof --------------------------------------------
TPUFRAME_TPU_TESTS=1 run fa_tpu_tests2 1800 \
    python -m pytest tests/test_flash_attention_tpu.py -v
run prec_probe 900 python perf/exp_precision_probe.py

# --- 2. the byte census ---------------------------------------------------
run hlo_dump 1800 python perf/exp_hlo_dump.py

# --- 3. bench regeneration (corrected MFU accounting, honest timing) -----
for b in 256 192 320 384 512 768 1024; do
  TPUFRAME_BENCH_BATCH=$b run bench_b$b 1200 python bench.py
done
TPUFRAME_BENCH_BATCH=256 TPUFRAME_BENCH_STEM=space_to_depth \
    run bench_s2d_256 1200 python bench.py
# Same-config rerun of the historical batch-512 s2d point (the PERF.md
# '2347 vs 2332' A/B) so retiring the old artifact loses no data point.
TPUFRAME_BENCH_BATCH=512 TPUFRAME_BENCH_STEM=space_to_depth \
    run bench_s2d_512 1200 python bench.py
# Retire the two stale-named artifacts ONLY once their reruns hold a real
# (non-degraded) measurement — bench.py emits a value-0.0 degraded record
# on watchdog timeout, which must not destroy the only prior measurement.
ok_bench() { python - "$1" <<'EOF'
import json, sys
try:
    rec = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
    sys.exit(0 if rec.get("value", 0) > 0 and not rec.get("degraded") else 1)
except Exception:
    sys.exit(1)
EOF
}
if ok_bench perf/results/bench_b512.out; then
  rm -f perf/results/bench_default.out perf/results/bench_default.err
fi
if ok_bench perf/results/bench_s2d_512.out; then
  rm -f perf/results/bench_s2d.out perf/results/bench_s2d.err
fi

# --- 4. convergence + crash/resume proof ---------------------------------
note "START exp_convergence (sub-script, has its own claim/retry phases)"
bash perf/exp_convergence.sh >> "$LOG" 2>&1
note "END exp_convergence rc=$?"

# --- 5. honest attention + breakdown timings -----------------------------
run attn_bench2 2400 python perf/bench_attention.py
run breakdown2 1800 python perf/exp_breakdown.py

# --- 6. transformer A/Bs -------------------------------------------------
MODEL=lm XENT=fused run tf_lm_fusedxent 2400 python perf/bench_transformer.py
MODEL=lm XENT=fused LM_BATCH=2 LM_SEQ=8192 \
    run tf_lm_8k 2400 python perf/bench_transformer.py
MODEL=lm XENT=fused LM_BATCH=1 LM_SEQ=32768 ATTN_ONLY=pallas \
    run tf_lm_32k 2400 python perf/bench_transformer.py
MODEL=bert BERT_BATCH=256 run tf_bert_b256 1800 python perf/bench_transformer.py
MODEL=lm XENT=fused REMAT=0 run tf_lm_noremat 2400 python perf/bench_transformer.py
MODEL=lm REMAT=0 run tf_lm_noremat_dense 2400 python perf/bench_transformer.py

# --- 7. live autotune demo ----------------------------------------------
TPUFRAME_BENCH_BATCH=256 TPUFRAME_BENCH_STEPS=8 TPUFRAME_BENCH_WARMUP=2 \
    TPUFRAME_BENCH_BUDGET_S=850 \
    run autotune_demo 4200 python -m tpuframe.obs.autotune \
    --out perf/results/autotune_report.json --budget 4 --timeout 900 \
    --axis "TPUFRAME_FUSION_THRESHOLD=,0,67108864" \
    -- python bench.py

note "queue 4 complete"
if [ -x perf/run_all_tpu5.sh ] || [ -f perf/run_all_tpu5.sh ]; then
  note "chaining queue 5"
  bash perf/run_all_tpu5.sh
fi
