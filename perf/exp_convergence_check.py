"""Analysis for perf/exp_convergence.sh — turns the raw JSONL metric logs
into the convergence assertions the round-3 verdict asked for (loss curve
decreasing across an injected crash + async-ckpt resume; throughput held).

Pure host-side: no jax import, safe to run anytime.  Prints one JSON
object (committed as perf/results/conv_summary.json) with pass/fail per
assertion so the claim is checkable from the artifact alone.
"""

from __future__ import annotations

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
RES = os.environ.get("CONV_RESULTS_DIR", os.path.join(HERE, "results"))

# Expected run shape (exp_convergence.sh's numbers; overridable so the
# analysis logic itself is testable on a miniature CPU run).
FAULT_STEP = int(os.environ.get("CONV_FAULT_STEP", "350"))
CKPT_EVERY = int(os.environ.get("CONV_CKPT_EVERY", "150"))
LOG_EVERY = int(os.environ.get("CONV_LOG_EVERY", "10"))
RESUME_STEP = (FAULT_STEP // CKPT_EVERY) * CKPT_EVERY


def read_jsonl(name: str, prefix: str = "train") -> list[dict]:
    path = os.path.join(RES, name)
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("prefix") == prefix:
                out.append(rec)
    return out


def windowed_means(series: list[tuple[int, float]], k: int = 5):
    """Mean loss over consecutive windows of k logged points."""
    vals = [v for _, v in series]
    return [sum(vals[i:i + k]) / len(vals[i:i + k])
            for i in range(0, len(vals), k)]


def main() -> int:
    a = read_jsonl("conv_a.jsonl")
    b = read_jsonl("conv_b.jsonl")
    r50 = read_jsonl("conv_r50.jsonl")
    evals = read_jsonl("conv_a.jsonl", "eval") + read_jsonl("conv_b.jsonl",
                                                            "eval")
    summary: dict = {"experiment": "convergence+crash-resume (round 4)"}
    ok = True

    # --- A: the cifar run, killed at 350, resumed from ckpt-300 ---------
    la = [(r["step"], r["loss"]) for r in a if "loss" in r]
    lb = [(r["step"], r["loss"]) for r in b if "loss" in r]
    if not la or not lb:
        summary["cifar"] = {"ok": False,
                            "error": f"missing logs (A={len(la)} B={len(lb)})"}
        print(json.dumps(summary, indent=1))
        return 1

    last_a = max(s for s, _ in la)
    first_b = min(s for s, _ in lb)
    # The run must resume from SOME committed checkpoint at or below the
    # last one written before the crash — with ckpt_async the step-RESUME
    # snapshot's COMMIT may legitimately not be durable when os._exit
    # fires, in which case falling back to the previous committed ckpt is
    # exactly the torn-checkpoint contract, not a failure.
    resume_base = ((first_b - 1) // CKPT_EVERY) * CKPT_EVERY
    resume_gap_ok = (CKPT_EVERY <= resume_base <= RESUME_STEP
                     and first_b - resume_base <= LOG_EVERY
                     and FAULT_STEP - LOG_EVERY <= last_a < FAULT_STEP)
    # Loss continuity across the crash: first resumed window vs last
    # pre-crash window (resume replays steps RESUME..FAULT with identical
    # data order, so the curve should CONTINUE, not reset to init-level).
    tail_a = [v for s, v in la if s > resume_base]
    head_b = [v for s, v in lb if s <= FAULT_STEP]
    init_a = [v for s, v in la if s <= 3 * LOG_EVERY]
    continuity_ok = bool(tail_a and head_b and
                         abs(sum(head_b) / len(head_b)
                             - sum(tail_a) / len(tail_a))
                         < 0.25 * max(1e-9, sum(init_a) / len(init_a)
                                      - sum(tail_a) / len(tail_a)))

    full = sorted(la + [p for p in lb if p[0] > last_a])
    wm = windowed_means(full, 5)
    drops = sum(1 for i in range(1, len(wm)) if wm[i] < wm[i - 1])
    decreasing_ok = (wm[-1] < wm[0] and full[-1][1] < 0.5 * full[0][1]
                     and drops >= 0.7 * (len(wm) - 1))

    warm_cut = int(os.environ.get("CONV_WARM_STEP", "100"))
    rates = [r["examples_per_sec"] for r in (a + b)
             if "examples_per_sec" in r and r["step"] > warm_cut]
    if rates:
        mean_r = sum(rates) / len(rates)
        var = sum((x - mean_r) ** 2 for x in rates) / len(rates)
        cv = (var ** 0.5) / mean_r
    else:
        mean_r, cv = 0.0, 1.0

    acc = [(r["step"], r.get("accuracy")) for r in evals
           if r.get("accuracy") is not None]
    # Throughput must HOLD across the run (the verdict's "within 5%"): gate
    # on the relative spread of the post-warmup per-window rates.
    throughput_ok = bool(rates and cv < 0.05)
    summary["cifar"] = {
        "ok": bool(resume_gap_ok and continuity_ok and decreasing_ok
                   and throughput_ok),
        "steps_logged": len(full),
        "last_step_before_crash": last_a,
        "first_step_after_resume": first_b,
        "resumed_from_ckpt_step": resume_base,
        "resume_from_committed_ckpt_ok": resume_gap_ok,
        "loss_first": round(full[0][1], 4),
        "loss_at_crash": round(tail_a[-1], 4) if tail_a else None,
        "loss_final": round(full[-1][1], 4),
        "windowed_means": [round(v, 4) for v in wm],
        "curve_decreasing_ok": decreasing_ok,
        "loss_continuity_across_crash_ok": continuity_ok,
        "eval_accuracy": [(s, round(v, 4)) for s, v in acc],
        "throughput_mean_ex_per_sec": round(mean_r, 1),
        "throughput_cv": round(cv, 4),
        "throughput_steady_ok": throughput_ok,
    }
    ok &= summary["cifar"]["ok"]

    # --- B: resnet50 sustained run vs the bench steady state -----------
    if r50:
        lr50 = [(r["step"], r["loss"]) for r in r50 if "loss" in r]
        rates50 = [r["examples_per_sec_per_chip"] for r in r50
                   if "examples_per_sec_per_chip" in r
                   and r["step"] > warm_cut]
        bench_val = None
        try:
            with open(os.path.join(RES, "bench_b256.out")) as fh:
                bench_val = json.loads(
                    fh.read().strip().splitlines()[-1])["value"]
        except Exception:
            pass
        steady = (sorted(rates50)[len(rates50) // 2] if rates50 else 0.0)
        wm50 = windowed_means(sorted(lr50), 5)
        summary["resnet50_synthetic"] = {
            "steps_logged": len(lr50),
            "loss_first": round(lr50[0][1], 4) if lr50 else None,
            "loss_final": round(lr50[-1][1], 4) if lr50 else None,
            "windowed_means": [round(v, 4) for v in wm50],
            "curve_decreasing_ok": bool(wm50 and wm50[-1] < wm50[0]),
            "harness_img_per_sec_per_chip_median": round(steady, 1),
            "bench_device_only_img_per_sec": bench_val,
            "harness_vs_bench": (round(steady / bench_val, 4)
                                 if bench_val else None),
        }
        # The harness number includes the real input pipeline + logging; vs
        # bench.py's device-only loop.  Record the ratio rather than
        # asserting 0.95 blindly — if infeed over the relay dominates, that
        # is a finding to report, not to hide.
        ok &= bool(wm50 and wm50[-1] < wm50[0])

    summary["ok"] = bool(ok)
    print(json.dumps(summary, indent=1))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
