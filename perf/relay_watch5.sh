#!/bin/bash
# Round-5 relay watcher — the round-4 lesson operationalized.
#
# The chip was reachable for ~2 MINUTES in all of round 4 (03:47-03:49
# UTC); the builder happened to be watching and landed FA 10/10 in that
# window.  Round 5 must not depend on luck: this watcher polls the
# relay tunnel ports once a minute and, the moment one accepts a TCP
# connection, fires the declared on-chip queue chain
#   run_all_tpu4b.sh  (bench regen -> convergence+crash/resume ->
#                      attention/breakdown -> transformer A/Bs ->
#                      autotune demo -> chains queue 5 -> census)
#   run_all_tpu6.sh   (scheduler-flag A/Bs)
# exactly the order PERF.md §10 / VERDICT round-4 #1 prescribe.
#
# One-shot: fires the chain once, waits for it, then exits (the chain's
# own claim.sh machinery handles mid-queue outages and re-claims).
# perf/STOP halts both this watcher and the queues (claim.sh sentinel),
# so the driver's end-of-round bench.py is never blocked behind us.
set -u
cd "$(dirname "$0")/.."
LOG=perf/results/relay_watch5.log
mkdir -p perf/results
note() { echo "[watch5 $(date -u +%FT%TZ)] $*" >> "$LOG"; }

relay_open() {
  python - <<'PYEOF'
import os, socket, sys
host = (os.environ.get("PALLAS_AXON_POOL_IPS") or "127.0.0.1").split(",")[0]
ports = os.environ.get("TPUFRAME_RELAY_PORTS", "8083,8082,8081")
for port in (int(p) for p in ports.split(",") if p.strip()):
    s = socket.socket(); s.settimeout(2.0)
    try:
        s.connect((host, port)); sys.exit(0)
    except OSError:
        continue
    finally:
        s.close()
sys.exit(1)
PYEOF
}

note "watcher started (pid $$); polling every 60s"
# ~11.5h of polling, bounded so a forgotten watcher cannot outlive the round.
for i in $(seq 1 690); do
  if [ -e perf/STOP ]; then note "STOP sentinel; exiting"; exit 0; fi
  if relay_open; then
    note "RELAY OPEN on poll $i — firing queue chain (4b -> 5 -> census -> 6)"
    bash perf/run_all_tpu4b.sh >> "$LOG" 2>&1
    note "queue 4b/5 chain exited rc=$?"
    if [ -e perf/STOP ]; then note "STOP sentinel after 4b; not starting 6"; exit 0; fi
    bash perf/run_all_tpu6.sh >> "$LOG" 2>&1
    note "queue 6 exited rc=$?"
    if [ -e perf/STOP ]; then note "STOP sentinel after 6; not starting 7"; exit 0; fi
    bash perf/run_all_tpu7.sh >> "$LOG" 2>&1
    note "queue 7 exited rc=$?"
    note "chain complete; watcher exiting"
    exit 0
  fi
  sleep 60
done
note "watch window exhausted without a relay opening"
