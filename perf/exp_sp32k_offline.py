"""Offline AOT compile of the FULL lm_long shape: dp2 x sp4 ring-attention
TransformerLM 124M at seq 32768 on 8 compile-only v5e devices.

History (PERF.md §9): this config had never been compiled at real scale —
CI exercises tiny shapes, and the first AOT attempt OOM'd at 39-43 GB/dev
from two stacked-residual classes the tiny tests cannot see:
  1. whole-chunk ring scores ([B,N,8192,8192] f32 per stage) — fixed by
     q-sub-chunking (`seq_parallel._chunk_attn(q_chunk=...)`);
  2. lax.scan/lax.map backward STACKING the masked-softmax residuals
     across ring stages and sub-chunks ([4,8,1,12,1024,8192] f32 = 12 GB
     buffers) — fixed by jax.checkpoint at both loop levels.
After both fixes the step compiles at 3.64 GB/dev temp.

Appends a JSON line to perf/results/offline_ab.jsonl.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _common import ensure_cpu_backend, to_shape_structs  # noqa: E402

ensure_cpu_backend()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from tpuframe import models  # noqa: E402
from tpuframe.ops import fused_xent as fx  # noqa: E402
from tpuframe.parallel import mesh as mesh_lib  # noqa: E402
from tpuframe.parallel import step as step_lib  # noqa: E402

SEQ = int(os.environ.get("SEQ", "32768"))
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results",
                   "offline_ab.jsonl")


def main():
    topo = topologies.get_topology_desc("v5e:2x4", platform="tpu")
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=2, seq=4),
                              devices=list(topo.devices))
    model = models.get_model(
        "transformer-lm", hidden_size=768, num_layers=12, num_heads=12,
        intermediate_size=3072, vocab_size=32000, max_seq=SEQ,
        seq_mode="ring", remat=True, dtype="bfloat16")
    repl = NamedSharding(mesh, P())
    part = P(mesh_lib.BATCH_AXES, "seq")
    ids = jax.ShapeDtypeStruct((2, SEQ), jnp.int32,
                               sharding=NamedSharding(mesh, part))
    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, SEQ), jnp.int32)),
        jax.random.key(0))
    tx = optax.adamw(3e-4)

    def loss_fn(params, model_state, b, rng):
        hidden = model.apply({"params": params}, b["input_ids"], train=True,
                             rngs={"dropout": rng}, hidden_only=True)
        w = params["lm_head"]["kernel"]
        loss = jnp.mean(fx.fused_softmax_xent(hidden, w, b["labels"]))
        return loss, ({}, {})

    state = jax.eval_shape(
        lambda v: step_lib.TrainState.create(v["params"], tx), variables)
    state = to_shape_structs(state, repl)
    step = step_lib.make_train_step(
        loss_fn, tx, mesh, donate=False, batch_partition=part,
        reduce_axes=(*mesh_lib.BATCH_AXES, "seq"))
    batch = {"input_ids": ids, "labels": ids}
    print(f"compiling dp2 x sp4 ring-attention LM at seq {SEQ}...",
          flush=True)
    c = jax.jit(step).lower(state, batch).compile()
    txt = c.as_text()
    ca = c.cost_analysis() or {}
    ma = c.memory_analysis()
    row = {"tag": f"lm_{SEQ//1024}k_sp_ring_dp2sp4",
           "devices": 8, "seq": SEQ, "batch": 2,
           "bytes": ca.get("bytes accessed", 0.0),
           "gb_per_dev": round(ca.get("bytes accessed", 0.0) / 1e9, 2),
           "flops_per_dev": ca.get("flops", 0.0),
           "temp_gb_per_dev": round(ma.temp_size_in_bytes / 1e9, 2),
           "collective_permutes": (txt.count("collective-permute(")
                                   + txt.count("collective-permute-start(")),
           "source": "offline AOT v5e topology compile"}
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row))


if __name__ == "__main__":
    main()
