"""Scaling-efficiency projection 8 -> 32 chips — the declared methodology.

The driver metric (BASELINE.json:2) is 8->32-chip scaling efficiency, but
this sandbox exposes ONE chip (BASELINE.md). SURVEY.md §6/§7 ("hard part
5") asks for an honest methodology defined up front; this script is it:

1. MEASURED: compile the real DP train step on a virtual 8-device mesh and
   read the cross-replica traffic out of the compiled HLO — the all-reduce
   operand bytes per step (for ResNet-50 DP: the fp32 gradient tree, ~97 MB,
   fused into one variadic all-reduce; asserted by tests/test_fusion.py).
   Collective bytes are a property of the program, not of the device, so
   the CPU-mesh HLO is the TPU program's traffic model.
2. MEASURED: single-chip step time from bench.py on the real chip.
3. DOCUMENTED CONSTANTS: per-chip ICI bandwidth from public spec sheets.
4. MODEL: bidirectional-ring all-reduce cost 2*(N-1)/N * B / BW per step,
   reported both unoverlapped (worst case: efficiency = t_c / (t_c + t_ar))
   and fully-overlapped (best case: t = max(t_c, t_ar)) — the truth lands
   between; XLA's latency-hiding scheduler targets the overlapped end.

Run on CPU (the HLO half) — it prints the projection table and the exact
formula inputs so a reader can re-derive every number.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# --bytes-only N: print {"n_devices": N, "ar_bytes": B} as JSON and exit —
# the mode tests/test_scaling32.py uses to verify the projection's central
# assumption (all-reduce bytes are N-independent) at BOTH mesh endpoints.
_N_DEVICES = 8
if "--bytes-only" in sys.argv:
    _N_DEVICES = int(sys.argv[sys.argv.index("--bytes-only") + 1])

os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_N_DEVICES}")

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax

jax.config.update("jax_platforms", "cpu")

from tpuframe import models
from tpuframe.models import losses
from tpuframe.parallel import mesh as mesh_lib
from tpuframe.parallel import step as step_lib

# Public spec-sheet constants (bytes/s). v5e: 1600 Gbps ICI per chip
# (Google Cloud TPU v5e spec); v4: 2400 Gbps. Ring all-reduce uses the
# bidirectional torus links; we model per-chip injection bandwidth.
ICI_BYTES_PER_S = {"v4": 300e9, "v5e": 200e9}

# Measured on the bench chip (BASELINE.md round 3): batch 256/chip.
MEASURED_IMG_PER_S = 2385.0
MEASURED_BATCH = 256
CHIP = "v5e"


def collective_bytes_per_step(n_devices: int = 8) -> int:
    """Compile the DP ResNet-50 step on an ``n_devices`` virtual mesh; sum
    the all-reduce operand bytes in the optimized HLO."""
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(data=n_devices))
    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    batch = max(16, 2 * n_devices)
    x = jnp.asarray(rng.normal(size=(batch, 64, 64, 3)), jnp.bfloat16)
    y = jnp.asarray(rng.integers(0, 1000, size=(batch,)), jnp.int32)
    variables = model.init(jax.random.key(0), x[:2])
    tx = optax.sgd(0.1, momentum=0.9)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        return losses.softmax_cross_entropy(logits, batch["label"]), (
            dict(mutated), {})

    state = step_lib.TrainState.create(
        variables["params"], tx,
        model_state={"batch_stats": variables["batch_stats"]})
    state = step_lib.replicate_state(state, mesh)
    step = step_lib.make_train_step(loss_fn, tx, mesh, donate=False)
    batch = {"image": jax.device_put(x, mesh_lib.batch_sharding(mesh)),
             "label": jax.device_put(y, mesh_lib.batch_sharding(mesh))}
    txt = step.lower(state, batch).compile().as_text()

    total = 0
    # HLO form: %all-reduce.N = (f32[256]{0}, ...) all-reduce(%op, ...) —
    # the reduced tensors are the RESULT tuple's types; operands are
    # unshaped value refs.  Sum result bytes across every all-reduce.
    for line in txt.splitlines():
        m = re.search(r"= (.*?) all-reduce(?:-start)?\(", line)
        if not m:
            continue
        for dt, dims in re.findall(r"(f32|bf16|f16|s32)\[([0-9,]*)\]",
                                   m.group(1)):
            size = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4}[dt]
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            total += n * size
    return total


def project(ar_bytes: int):
    t_c = MEASURED_BATCH / MEASURED_IMG_PER_S  # compute-side step seconds
    bw = ICI_BYTES_PER_S[CHIP]
    print(f"inputs: all-reduce bytes/step={ar_bytes/1e6:.1f}MB "
          f"(compiled HLO, 8-dev mesh), single-chip step={t_c*1e3:.1f}ms "
          f"({MEASURED_IMG_PER_S} img/s at batch {MEASURED_BATCH}, "
          f"BASELINE.md), ICI={bw/1e9:.0f}GB/s/chip ({CHIP} spec)")
    print(f"{'chips':>6} {'t_ar(ms)':>9} {'eff(no-overlap)':>16} "
          f"{'eff(overlapped)':>16}")
    rows = {}
    for n in (8, 16, 32, 64):
        t_ar = 2 * (n - 1) / n * ar_bytes / bw
        eff_worst = t_c / (t_c + t_ar)
        eff_best = t_c / max(t_c, t_ar)
        rows[n] = (t_ar, eff_worst, eff_best)
        print(f"{n:>6} {t_ar*1e3:>9.2f} {eff_worst:>15.1%} "
              f"{eff_best:>15.1%}")
    w8, b8 = rows[8][1], rows[8][2]
    w32, b32 = rows[32][1], rows[32][2]
    print(f"8->32 relative efficiency: worst {w32/w8:.1%}, "
          f"best {b32/b8:.1%} (target: >=90% of the Horovod-GPU baseline, "
          f"BASELINE.json:5; the Horovod paper's own anchor is ~88% at "
          f"128 GPUs)")


if __name__ == "__main__":
    b = collective_bytes_per_step(_N_DEVICES)
    if "--bytes-only" in sys.argv:
        import json

        print(json.dumps({"n_devices": _N_DEVICES, "ar_bytes": b}))
    else:
        project(b)
