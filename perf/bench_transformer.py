"""Transformer train-step throughput on the real chip — BERT (config 4's
allreduce-stress model) and the TransformerLM long-context flagship.

VERDICT r2 #3: config 4 and the LM had zero on-chip evidence.  Measures
examples/s (BERT) and tokens/s (LM, both attention impls), bf16.  Results
go into BASELINE.md.

    python perf/bench_transformer.py           # both models
    MODEL=bert python perf/bench_transformer.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import make_log, setup

jax = setup()
import jax.numpy as jnp
import numpy as np
import optax

from tpuframe.models import losses
from tpuframe.parallel import step as step_lib

MODEL = os.environ.get("MODEL", "both")
STEPS = int(os.environ.get("N", "10"))
BERT_BATCH = int(os.environ.get("BERT_BATCH", "128"))
BERT_SEQ = int(os.environ.get("BERT_SEQ", "128"))
LM_BATCH = int(os.environ.get("LM_BATCH", "8"))
LM_SEQ = int(os.environ.get("LM_SEQ", "2048"))


log = make_log("tf-bench")


def run_chain(step, state, batch, steps=STEPS):
    state, m = step(state, batch)
    float(m["loss"])  # compile + settle
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step(state, batch)
    float(m["loss"])
    return (time.perf_counter() - t0) / steps


def bench_bert():
    from tpuframe.models import bert as bert_lib

    cfg = bert_lib.BertConfig(dtype="bfloat16")  # base, MXU compute
    model = bert_lib.BertForSequenceClassification(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(BERT_BATCH, BERT_SEQ)
                       ).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids),
             "attention_mask": jnp.ones((BERT_BATCH, BERT_SEQ), jnp.int32),
             "token_type_ids": jnp.zeros((BERT_BATCH, BERT_SEQ), jnp.int32),
             "label": jnp.asarray(rng.integers(0, 2, size=(BERT_BATCH,)),
                                  jnp.int32)}
    variables = model.init(jax.random.key(0), batch["input_ids"][:1],
                           batch["attention_mask"][:1],
                           batch["token_type_ids"][:1])
    tx = optax.adamw(2e-5)

    def loss_fn(params, model_state, b, rng):
        logits = model.apply({"params": params}, b["input_ids"],
                             b["attention_mask"], b["token_type_ids"],
                             train=True, rngs={"dropout": rng})
        return losses.softmax_cross_entropy(logits, b["label"]), ({}, {})

    state = step_lib.TrainState.create(variables["params"], tx)
    step = step_lib.make_train_step(loss_fn, tx, None, donate=True)
    dt = run_chain(step, state, batch)
    ex_s = BERT_BATCH / dt
    log(f"bert-base b={BERT_BATCH} s={BERT_SEQ}: {dt*1e3:.1f} ms/step, "
        f"{ex_s:.1f} examples/s, {ex_s*BERT_SEQ:.0f} tokens/s")
    return {"model": "bert-base", "batch": BERT_BATCH, "seq": BERT_SEQ,
            "ms_per_step": round(dt * 1e3, 1),
            "examples_per_s": round(ex_s, 1),
            "tokens_per_s": round(ex_s * BERT_SEQ)}


def bench_lm(attn_impl):
    from tpuframe.models.transformer_lm import LMConfig, TransformerLM

    remat = os.environ.get("REMAT", "1") == "1"
    cfg = LMConfig(vocab_size=32000, hidden_size=768, num_layers=12,
                   num_heads=12, intermediate_size=3072, max_seq=LM_SEQ,
                   dtype="bfloat16", attn_impl=attn_impl, remat=remat)
    model = TransformerLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(LM_BATCH, LM_SEQ + 1)
                       ).astype(np.int32)
    batch = {"input_ids": jnp.asarray(ids[:, :-1]),
             "labels": jnp.asarray(ids[:, 1:])}
    variables = model.init(jax.random.key(0), batch["input_ids"][:1])
    tx = optax.adamw(1e-4)

    fused = os.environ.get("XENT", "dense") == "fused"
    if fused:
        # Chunked fused head+loss (tpuframe.ops.fused_xent): the [B,S,V]
        # logits never materialize in HBM.
        from tpuframe.ops import fused_xent as fx

        def loss_fn(params, model_state, b, rng):
            hidden = model.apply({"params": params}, b["input_ids"],
                                 train=True, rngs={"dropout": rng},
                                 hidden_only=True)
            w = params["lm_head"]["kernel"]
            loss = jnp.mean(fx.fused_softmax_xent(hidden, w, b["labels"]))
            return loss, ({}, {})
    else:
        def loss_fn(params, model_state, b, rng):
            logits = model.apply({"params": params}, b["input_ids"],
                                 train=True, rngs={"dropout": rng})
            return losses.softmax_cross_entropy(logits, b["labels"]), ({}, {})

    state = step_lib.TrainState.create(variables["params"], tx)
    step = step_lib.make_train_step(loss_fn, tx, None, donate=True)
    dt = run_chain(step, state, batch)
    tok_s = LM_BATCH * LM_SEQ / dt
    mods = (("" if remat else ",no-remat")
            + (",fused-xent" if fused else ""))
    tag = f"lm(124M,{attn_impl}{mods})"
    log(f"{tag} b={LM_BATCH} s={LM_SEQ}: {dt*1e3:.1f} ms/step,"
        f" {tok_s:.0f} tokens/s")
    return {"model": f"transformer-lm/{attn_impl}" + mods.replace(",", "/"),
            "batch": LM_BATCH, "seq": LM_SEQ,
            "ms_per_step": round(dt * 1e3, 1),
            "tokens_per_s": round(tok_s)}


def main():
    log(f"backend={jax.default_backend()}")
    rows = []
    if MODEL in ("both", "bert"):
        rows.append(bench_bert())
    if MODEL in ("both", "lm"):
        only = os.environ.get("ATTN_ONLY", "")
        impls = (only,) if only else ("xla", "pallas")
        # xla attention materializes [B,H,S,S] f32 scores; refuse shapes
        # that can't fit rather than crash the relay's compile helper.
        score_gb = LM_BATCH * 12 * LM_SEQ * LM_SEQ * 4 / 1e9
        if "xla" in impls and score_gb > 4:
            log(f"skipping xla attention: scores ~{score_gb:.0f}GB")
            impls = tuple(i for i in impls if i != "xla")
        for impl in impls:
            try:
                rows.append(bench_lm(impl))
            except Exception as e:  # noqa: BLE001
                rows.append({"model": f"transformer-lm/{impl}",
                             "error": f"{type(e).__name__}: {e}"[:300]})
                log(rows[-1]["error"])
    import json
    print(json.dumps(rows, indent=1), flush=True)


if __name__ == "__main__":
    main()
