#!/bin/bash
# Round-5 TPU queue #7 — the fused conv+BN backward A/B (PERF.md §6.3's
# byte-floor lever, built this round as tpuframe/ops/fused_conv_bn.py).
#
# The offline AOT census verifies the BYTE claim without the chip; this
# queue measures the ms/step consequence on the real v5e:
#   1. bench with bn=fused at the 256 optimum and at 512
#   2. fresh unfused runs in the same session (same clock/thermal state)
# Run AFTER queues 4b/5/6 (chip claim + one-client rules via claim.sh).
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_all7.log
echo "=== run_all_tpu7 $(date -u +%FT%TZ) ===" >> "$LOG"
. perf/claim.sh

note() { echo "[run_all7 $(date -u +%T)] $*" | tee -a "$LOG"; }

claim_wait_for_others | tee -a "$LOG"
note "phase 0: chip claim"
if ! claim_chip 96 "$LOG"; then
  note "claim FAILED; giving up"
  exit 1
fi

run() { queue_run "$@"; }

for b in 256 512; do
  TPUFRAME_BENCH_BATCH=$b TPUFRAME_BENCH_BN=fused \
      run bench_b${b}_fusedbn 1800 python bench.py
  TPUFRAME_BENCH_BATCH=$b \
      run bench_b${b}_ab_unfused 1200 python bench.py
done

note "queue 7 complete"
