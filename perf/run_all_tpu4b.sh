#!/bin/bash
# Recovery continuation of run_all_tpu4.sh (2026-07-31): the original
# queue's hlo_dump run hung >30 min, its timeout SIGTERM wedged the chip
# grant, and the next runs burned their timeouts against the wedge without
# matching the old outage signatures.  This queue:
#   - carries every remaining queue-4 item (bench regeneration, s2d,
#     convergence + crash/resume, honest attention/breakdown timings,
#     transformer A/Bs, autotune demo), then chains queue 5 unchanged;
#   - moves the byte census (hlo_dump — the hang suspect) to the END,
#     at B=256 with per-phase progress logging;
#   - relies on claim.sh's new mode-3 outage rule (rc=124 => re-claim +
#     retry once) so a wedge can no longer cascade.
# Relay rules (PERF.md §0): ONE client, strictly serial.
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_all4.log
echo "=== run_all_tpu4b $(date -u +%FT%TZ) ===" >> "$LOG"
. perf/claim.sh

note() { echo "[run_all4b $(date -u +%T)] $*" | tee -a "$LOG"; }

claim_wait_for_others | tee -a "$LOG"

note "phase 0: probing for chip claim (retry loop)..."
if ! claim_chip 96 "$LOG"; then
  note "phase 0 FAILED — relay wedged for the whole window; giving up"
  exit 1
fi
note "chip claimed — running queue 4b"

run() { queue_run "$@"; }

# --- bench regeneration (corrected MFU accounting, honest timing) --------
for b in 256 192 320 384 512 768 1024; do
  TPUFRAME_BENCH_BATCH=$b run bench_b$b 1200 python bench.py
done
TPUFRAME_BENCH_BATCH=256 TPUFRAME_BENCH_STEM=space_to_depth \
    run bench_s2d_256 1200 python bench.py
TPUFRAME_BENCH_BATCH=512 TPUFRAME_BENCH_STEM=space_to_depth \
    run bench_s2d_512 1200 python bench.py
ok_bench() { python - "$1" <<'EOF'
import json, sys
try:
    rec = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
    sys.exit(0 if rec.get("value", 0) > 0 and not rec.get("degraded") else 1)
except Exception:
    sys.exit(1)
EOF
}
if ok_bench perf/results/bench_b512.out; then
  rm -f perf/results/bench_default.out perf/results/bench_default.err
fi
if ok_bench perf/results/bench_s2d_512.out; then
  rm -f perf/results/bench_s2d.out perf/results/bench_s2d.err
fi

# --- convergence + crash/resume proof ------------------------------------
note "START exp_convergence (sub-script, has its own claim/retry phases)"
bash perf/exp_convergence.sh >> "$LOG" 2>&1
note "END exp_convergence rc=$?"

# --- honest attention + breakdown timings --------------------------------
run attn_bench2 2400 python perf/bench_attention.py
run breakdown2 1800 python perf/exp_breakdown.py

# --- transformer A/Bs ----------------------------------------------------
MODEL=lm XENT=fused run tf_lm_fusedxent 2400 python perf/bench_transformer.py
MODEL=lm XENT=fused LM_BATCH=2 LM_SEQ=8192 \
    run tf_lm_8k 2400 python perf/bench_transformer.py
MODEL=lm XENT=fused LM_BATCH=1 LM_SEQ=32768 ATTN_ONLY=pallas \
    run tf_lm_32k 2400 python perf/bench_transformer.py
MODEL=bert BERT_BATCH=256 run tf_bert_b256 1800 python perf/bench_transformer.py
MODEL=lm XENT=fused REMAT=0 run tf_lm_noremat 2400 python perf/bench_transformer.py
MODEL=lm REMAT=0 run tf_lm_noremat_dense 2400 python perf/bench_transformer.py

# --- live autotune demo --------------------------------------------------
TPUFRAME_BENCH_BATCH=256 TPUFRAME_BENCH_STEPS=8 TPUFRAME_BENCH_WARMUP=2 \
    TPUFRAME_BENCH_BUDGET_S=850 \
    run autotune_demo 4200 python -m tpuframe.obs.autotune \
    --out perf/results/autotune_report.json --budget 4 --timeout 900 \
    --axis "TPUFRAME_FUSION_THRESHOLD=,0,67108864" \
    -- python bench.py

note "queue 4b complete"
if [ -f perf/run_all_tpu5.sh ]; then
  note "chaining queue 5"
  bash perf/run_all_tpu5.sh
fi

# --- byte census LAST (the 2026-07-31 hang suspect) ----------------------
run hlo_dump 2400 python perf/exp_hlo_dump.py

note "queue 4b + census complete"
