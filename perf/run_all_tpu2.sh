#!/bin/bash
# Round-3 TPU queue #2: follow-ups from queue #1's findings.
#  - FA on-chip tests after the f32-tolerance fix (expect 8/8)
#  - Mosaic precision=HIGHEST probe (decides if f32 tolerance can tighten)
#  - attention + breakdown benches re-run with execution-cache-proof
#    chained timing (queue #1's numbers were fake ~20us replays)
#  - finer batch sweep around the async-timing optimum (256)
# Same relay rules as run_all_tpu.sh: ONE client, strictly serial.
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_all2.log
echo "=== run_all_tpu2 $(date -u +%FT%TZ) ===" >> "$LOG"

note() { echo "[run_all2 $(date -u +%T)] $*" | tee -a "$LOG"; }

# The wedged relay raises UNAVAILABLE from backend init after ~25 min of
# internal retries; a single blocking probe therefore gives up too early.
# Retry clean-exiting probes (never killed mid-claim) for up to ~5 h.
note "phase 0: probing for chip claim (retry loop, up to ~5h)..."
claimed=0
for attempt in $(seq 1 60); do
  timeout 2400 python -u -c "
import time; t0=time.time()
import jax, jax.numpy as jnp
(jnp.ones((256,256), jnp.bfloat16) @ jnp.ones((256,256), jnp.bfloat16)).block_until_ready()
print(f'CLAIM OK after {time.time()-t0:.1f}s', flush=True)
" >> "$LOG" 2>&1 && { claimed=1; break; }
  note "claim attempt $attempt failed; sleeping 180s"
  sleep 180
done
if [ "$claimed" != 1 ]; then
  note "phase 0 FAILED — relay wedged for the whole window; giving up"
  exit 1
fi
note "chip claimed — running queue 2"

run() { # name timeout cmd...
  local name=$1 tmo=$2; shift 2
  note "START $name"
  timeout "$tmo" "$@" > "perf/results/$name.out" 2> "perf/results/$name.err"
  note "END $name rc=$?"
}

# 1. FA on-chip proof, fixed f32 tolerances.
TPUFRAME_TPU_TESTS=1 run fa_tpu_tests2 1200 \
    python -m pytest tests/test_flash_attention_tpu.py -v
# 2. Mosaic precision probe.
run prec_probe 900 python perf/exp_precision_probe.py
# 3. Honest pallas-vs-xla attention sweep (chained timing).
run attn_bench2 2400 python perf/bench_attention.py
# 4. Honest step breakdown (chained timing).
run breakdown2 1800 python perf/exp_breakdown.py
# 5. Where do the 143 GB/step go — optimized HLO + layout census.
run hlo_dump 1800 python perf/exp_hlo_dump.py
# 6. Finer batch sweep near 256.
TPUFRAME_BENCH_BATCH=192 run bench_b192 1200 python bench.py
TPUFRAME_BENCH_BATCH=320 run bench_b320 1200 python bench.py
TPUFRAME_BENCH_BATCH=384 run bench_b384 1200 python bench.py
TPUFRAME_BENCH_BATCH=256 TPUFRAME_BENCH_STEM=space_to_depth \
    run bench_s2d_256 1200 python bench.py

note "queue 2 complete"
