"""Pallas flash attention vs the XLA einsum path on the real chip.

VERDICT r2 #2's measurement half: tokens/s fwd and fwd+bwd at seq 2k-8k,
causal, bf16 — the long-context shape class.  Results go into BASELINE.md.

    python perf/bench_attention.py            # all seqs, both impls
    SEQS=2048 python perf/bench_attention.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import make_log, setup, timeit

jax = setup()
import jax.numpy as jnp
import numpy as np

from tpuframe.ops import attention as attn_ops
from tpuframe.ops.flash_attention import flash_mha

SEQS = [int(s) for s in os.environ.get("SEQS", "2048,4096,8192").split(",")]
HEADS = int(os.environ.get("HEADS", "8"))
HEAD_DIM = int(os.environ.get("HEAD_DIM", "64"))
BATCH = int(os.environ.get("B", "4"))
STEPS = int(os.environ.get("N", "10"))


log = make_log("attn-bench")


def main():
    log(f"backend={jax.default_backend()} b={BATCH} h={HEADS} d={HEAD_DIM}")
    rows = []
    for s in SEQS:
        rng = np.random.default_rng(0)
        mk = lambda: jnp.asarray(  # noqa: E731
            rng.normal(0, 0.5, size=(BATCH, s, HEADS, HEAD_DIM)), jnp.bfloat16)
        q, k, v = mk(), mk(), mk()
        tokens = BATCH * s

        impls = {
            "pallas": jax.jit(lambda q, k, v: flash_mha(
                q, k, v, causal=True, interpret=False)),
            "xla": jax.jit(lambda q, k, v: attn_ops.multihead_attention(
                q, k, v, causal=True, impl="xla")),
        }
        grads = {
            name: jax.jit(jax.grad(
                lambda q, k, v, f=f: jnp.sum(f(q, k, v) ** 2).astype(jnp.float32),
                argnums=(0, 1, 2)))
            for name, f in impls.items()
        }
        for name in impls:
            try:
                t_f = timeit(impls[name], q, k, v, steps=STEPS)
                t_fb = timeit(grads[name], q, k, v, steps=STEPS)
                row = {"seq": s, "impl": name,
                       "fwd_ms": round(t_f * 1e3, 2),
                       "fwd_tokens_per_s": round(tokens / t_f),
                       "fwdbwd_ms": round(t_fb * 1e3, 2),
                       "fwdbwd_tokens_per_s": round(tokens / t_fb)}
            except Exception as e:  # noqa: BLE001 — record and continue
                row = {"seq": s, "impl": name,
                       "error": f"{type(e).__name__}: {e}"[:300]}
            rows.append(row)
            log(str(row))
    import json
    print(json.dumps(rows, indent=1), flush=True)


if __name__ == "__main__":
    main()
