"""Pallas flash attention vs the XLA einsum path on the real chip.

VERDICT r2 #2's measurement half: tokens/s fwd and fwd+bwd at seq 2k-8k,
causal, bf16 — the long-context shape class.  Results go into BASELINE.md.

Timing must be DATA-DEPENDENT on this relay platform: dispatching the same
compiled program on the same input buffers repeatedly returns in ~20us
regardless of the program's real cost (an execution cache somewhere in the
remote-execution path — independent repeats of a seq-8192 attention "ran"
1000x faster than its MXU roofline).  So each measurement jits a chain of
``n`` attention calls whose output feeds the next call's query, and the
per-call time is (t(n=N) - t(n=1)) / (N-1): execution-cache-proof (every
call's input differs), dispatch-overhead-free, still one HBM-resident loop.

    python perf/bench_attention.py            # all seqs, both impls
    SEQS=2048 python perf/bench_attention.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import make_log, setup, timeit_chain

jax = setup()
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpuframe.ops import attention as attn_ops
from tpuframe.ops.flash_attention import flash_mha

SEQS = [int(s) for s in os.environ.get("SEQS", "2048,4096,8192").split(",")]
HEADS = int(os.environ.get("HEADS", "8"))
HEAD_DIM = int(os.environ.get("HEAD_DIM", "64"))
BATCH = int(os.environ.get("B", "4"))
# Starting chain length; timeit_chain grows it until the timing difference
# clears the relay's round-trip jitter (perf/_common.py).
CHAIN = int(os.environ.get("N", "32"))

log = make_log("attn-bench")


def fwd_chain(f, n):
    """jit of n chained attention calls: out_i becomes query_{i+1}."""
    def g(q, k, v):
        def body(x, _):
            return f(x, k, v).astype(q.dtype), None
        x, _ = lax.scan(body, q, None, length=n)
        return x
    return jax.jit(g)


def fwdbwd_chain(f, n):
    """jit of grad-through-n-chained-calls: n forwards + n backwards."""
    def loss(q, k, v):
        def body(x, _):
            return f(x, k, v).astype(q.dtype), None
        x, _ = lax.scan(body, q, None, length=n)
        return jnp.sum(x.astype(jnp.float32) ** 2)
    return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))


def main():
    log(f"backend={jax.default_backend()} b={BATCH} h={HEADS} d={HEAD_DIM} "
        f"chain={CHAIN}")
    rows = []
    for s in SEQS:
        rng = np.random.default_rng(0)
        mk = lambda: jnp.asarray(  # noqa: E731
            rng.normal(0, 0.5, size=(BATCH, s, HEADS, HEAD_DIM)), jnp.bfloat16)
        q, k, v = mk(), mk(), mk()
        tokens = BATCH * s

        impls = {
            "pallas": lambda q, k, v: flash_mha(
                q, k, v, causal=True, interpret=False),
            "xla": lambda q, k, v: attn_ops.multihead_attention(
                q, k, v, causal=True, impl="xla"),
        }
        # The materialized [B,H,S,S] f32 scores of the xla path: don't even
        # try shapes that cannot fit — the seq-8192 attempt crashed the
        # relay's remote-compile helper (perf/results/attn_bench.out, queue
        # 1) and helper crashes are a suspect for wedging the chip grant.
        score_gb = BATCH * HEADS * s * s * 4 / 1e9
        if score_gb > 4:
            rows.append({"seq": s, "impl": "xla",
                         "error": f"skipped: S^2 scores ~{score_gb:.0f}GB "
                                  f"exceed HBM (flash runs this shape)"})
            log(str(rows[-1]))
            impls.pop("xla")
        # grad-of-scan saves per-iteration residuals (~4 tensors of
        # b*s*h*d bf16 each); cap the bwd chain so they fit in ~4 GB of
        # HBM rather than letting the adaptive growth OOM the chip.
        resid_bytes = 4 * BATCH * s * HEADS * HEAD_DIM * 2
        max_bwd_chain = max(8, int(4e9 / resid_bytes))
        for name, f in impls.items():
            try:
                t_f = timeit_chain(
                    lambda n: fwd_chain(f, n), q, k, v, chain=CHAIN, log=log)
                t_fb = timeit_chain(
                    lambda n: fwdbwd_chain(f, n), q, k, v, chain=CHAIN,
                    log=log, max_chain=max_bwd_chain, min_delta=0.25)
                row = {"seq": s, "impl": name,
                       "fwd_ms": round(t_f * 1e3, 3),
                       "fwd_tokens_per_s": round(tokens / t_f),
                       "fwdbwd_ms": round(t_fb * 1e3, 3),
                       "fwdbwd_tokens_per_s": round(tokens / t_fb)}
            except Exception as e:  # noqa: BLE001 — record and continue
                row = {"seq": s, "impl": name,
                       "error": f"{type(e).__name__}: {e}"[:300]}
            rows.append(row)
            log(str(row))
    import json
    print(json.dumps(rows, indent=1), flush=True)


if __name__ == "__main__":
    main()
