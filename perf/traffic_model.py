"""Static HBM-traffic model of the ResNet-50 train step — the offline half
of the byte census (PERF.md §2).

`exp_breakdown.py` measured (on chip, batch 512): 143.5 GB accessed per
full step vs a ~45 GB naive activation estimate, i.e. ~3x inflation, and
the step is bandwidth-bound (81% of the HBM roofline).  `exp_hlo_dump.py`
attributes from the compiled HLO; THIS tool attributes from first
principles so the two can be cross-checked — and so attribution exists
even when the chip/relay is unavailable (the 2026-07-31 hang).

Model
-----
Enumerate every conv/BN/relu/pool/fc tensor of ResNet-50 v1.5 (NHWC,
bf16 activations, f32 params) and count HBM bytes under explicit,
stated assumptions:

  fwd (train):  conv reads in+w, writes out; BN-train reads the conv
                output twice more (batch-stats reduction pass + the
                normalize pass, which fuses scale/shift/relu and the
                next conv's read cannot — it needs the normalized
                value) and writes the normalized output once.
  bwd:          dx needs w + dy; dw needs saved-in + dy; BN bwd reads
                the saved normalized activation + dy and writes dy';
                per conv: reads 2x dy + saved in + w, writes dx + dw.
  optimizer:    SGD-momentum reads grads+params+momentum, writes
                params+momentum (5 x param bytes, f32).

Each tensor is counted twice: LOGICAL bytes (shape product x dtype) and
PADDED bytes (TPU (8,128) tiling on the two minor dims — the same rule
`exp_hlo_dump._nbytes` applies to real HLO layouts, minor dim to 128
lanes, next-minor to 8 sublanes).  The difference, grouped by feature
width, is the lane-padding attribution: C=3 inputs pad 42.7x, C=64 stem
tensors 2x, C>=128 not at all.

Run: python perf/traffic_model.py [batch]    (default 512)
"""

from __future__ import annotations

import dataclasses
import json
import sys


@dataclasses.dataclass
class T:
    """A tensor with its per-step HBM touch counts."""
    name: str
    shape: tuple[int, ...]      # NHWC activations / HWIO weights
    dtype_bytes: int
    fwd_touches: int            # reads+writes in the forward pass
    bwd_touches: int            # reads+writes in the backward pass
    group: str                  # attribution bucket

    def logical(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n * self.dtype_bytes

    def padded(self) -> int:
        dims = list(self.shape)
        if len(dims) >= 1:
            dims[-1] = -(-dims[-1] // 128) * 128
        if len(dims) >= 2:
            dims[-2] = -(-dims[-2] // 8) * 8
        n = 1
        for d in dims:
            n *= d
        return n * self.dtype_bytes


def _bottleneck(tensors, n, h, w, cin, cmid, cout, stride, name):
    """ResNet v1.5 bottleneck: 1x1 cin->cmid, 3x3 (stride) cmid->cmid,
    1x1 cmid->cout, projection cin->cout (stride) on the first block."""
    ho, wo = h // stride, w // stride
    proj = cin != cout
    convs = [
        (f"{name}.conv1", (1, 1, cin, cmid), (n, h, w, cin), (n, h, w, cmid)),
        (f"{name}.conv2", (3, 3, cmid, cmid), (n, h, w, cmid), (n, ho, wo, cmid)),
        (f"{name}.conv3", (1, 1, cmid, cout), (n, ho, wo, cmid), (n, ho, wo, cout)),
    ]
    if proj:
        convs.append((f"{name}.proj", (1, 1, cin, cout), (n, h, w, cin),
                      (n, ho, wo, cout)))
    for cname, wshape, ishape, oshape in convs:
        _conv_bn(tensors, cname, wshape, ishape, oshape)
    # Residual add: reads both branches, writes the sum (fused with the
    # final relu).  Counted once on the output shape.
    tensors.append(T(f"{name}.add", (n, ho, wo, cout), 2,
                     fwd_touches=3, bwd_touches=2, group=_grp(cout)))
    return ho, wo, cout


def _grp(c: int) -> str:
    if c < 8:
        return "C<8 (42x lane pad)"
    if c < 128:
        return "8<=C<128 (lane pad)"
    return "C>=128 (no pad)"


def _conv_bn(tensors, name, wshape, ishape, oshape):
    cin, cout = wshape[2], wshape[3]
    # conv: fwd reads in (counted on the producer's side as a write; we
    # count each activation's touches on ITS tensor) — bookkeeping: the
    # input read belongs to this conv but the tensor entry for the input
    # was already appended by the producer with its own write; to keep
    # attribution by tensor, touches below are per-tensor totals:
    #   activation out: fwd = conv-write + BN-stats read + BN-normalize
    #                   read + normalized write = 4 touches; the NEXT
    #                   layer's read adds 1 more (added by that layer via
    #                   `extra_read`).  bwd: saved-in read (next conv's
    #                   dw), dy read x2, dx write = handled symmetrically.
    # weights: fwd read + bwd read + dw write (f32).
    tensors.append(T(f"{name}.w", wshape, 4, fwd_touches=1, bwd_touches=2,
                     group="weights"))
    # input activation: one read by this conv (fwd) + one saved-read (bwd
    # dw) + one dx write (bwd).
    tensors.append(T(f"{name}.in_rd", ishape, 2, fwd_touches=1,
                     bwd_touches=2, group=_grp(ishape[-1])))
    # output activation: conv write + BN train chain (stats read +
    # normalize read + normalized write) (fwd); dy read x2 + dy' write (bwd).
    tensors.append(T(f"{name}.out", oshape, 2, fwd_touches=4, bwd_touches=3,
                     group=_grp(oshape[-1])))


def build(n: int):
    tensors: list[T] = []
    # Input + stem (7x7/2, BN, relu, maxpool 3x3/2).
    _conv_bn(tensors, "stem", (7, 7, 3, 64), (n, 224, 224, 3),
             (n, 112, 112, 64))
    # Pool input side: the maxpool reads the full-resolution stem output
    # (fwd) and writes dx at that shape (bwd) — 4x the output-side bytes.
    tensors.append(T("stem.pool_in", (n, 112, 112, 64), 2, fwd_touches=1,
                     bwd_touches=1, group=_grp(64)))
    tensors.append(T("stem.pool", (n, 56, 56, 64), 2, fwd_touches=2,
                     bwd_touches=2, group=_grp(64)))
    h = w = 56
    c = 64
    stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
              (3, 512, 2048, 2)]
    for si, (blocks, cmid, cout, stride) in enumerate(stages):
        for b in range(blocks):
            h, w, c = _bottleneck(tensors, n, h, w, c, cmid, cout,
                                  stride if b == 0 else 1, f"c{si+2}.b{b}")
    # Head: global avgpool + fc (input side counted at the c5 output shape).
    tensors.append(T("head.pool_in", (n, 7, 7, 2048), 2, fwd_touches=1,
                     bwd_touches=1, group=_grp(2048)))
    tensors.append(T("head.pool", (n, 1, 1, 2048), 2, fwd_touches=2,
                     bwd_touches=2, group=_grp(2048)))
    tensors.append(T("head.fc.w", (1, 1, 2048, 1000), 4, fwd_touches=1,
                     bwd_touches=2, group="weights"))
    tensors.append(T("head.logits", (n, 1, 1, 1000), 4, fwd_touches=2,
                     bwd_touches=2, group=_grp(1000)))
    return tensors


PARAM_COUNT = 25_557_032  # torchvision resnet50 reference (incl. BN)


def param_count(tensors) -> int:
    total = 0
    for t in tensors:
        if t.group != "weights":
            continue
        k = 1
        for d in t.shape:
            k *= d
        total += k
        # + BN scale/shift per conv output channel (2 x cout), fc bias.
        if t.name.endswith(".w") and not t.name.startswith("head.fc"):
            total += 2 * t.shape[3]
    total += 1000  # fc bias
    return total


# Variant B ("fusion-aware", calibrated against exp_breakdown.py's measured
# split at batch 512: fwd-train 38.1 GB, bwd ~105.2 GB, full 143.5 GB):
#   fwd: XLA fuses the BN normalize into the consumer's read (the
#        normalized activation never lands in HBM) — conv out is touched
#        only by its write + one batch-stats reduction read;
#   bwd: the expensive side — per conv output: dy read for dx, dy read
#        for dw, saved pre-BN read (recompute normalize for dw's input),
#        BN-backward's dgamma/dbeta reduction reads (pre-BN + dy), and
#        the dx write: 6 touches; input-side saved read + dx write: 2.
VARIANT_B = {".out": (2, 6), ".in_rd": (1, 2), ".add": (2, 2),
             ".pool_in": (1, 1), ".pool": (2, 2), ".w": (1, 2),
             ".logits": (2, 2)}


def _variant_b_touches(t: T) -> tuple[int, int]:
    for suffix, (f, b) in VARIANT_B.items():
        if t.name.endswith(suffix):
            return f, b
    return t.fwd_touches, t.bwd_touches


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    tensors = build(n)

    groups: dict[str, dict[str, float]] = {}
    fwd_l = bwd_l = 0
    bn_chain_l = 0
    for t in tensors:
        g = groups.setdefault(t.group, {"logical": 0, "padded": 0})
        touches = t.fwd_touches + t.bwd_touches
        g["logical"] += touches * t.logical()
        g["padded"] += touches * t.padded()
        fwd_l += t.fwd_touches * t.logical()
        bwd_l += t.bwd_touches * t.logical()
        if t.name.endswith(".out"):
            # The BN-train chain's extra touches beyond the conv write:
            # stats read + normalize read + normalized write.
            bn_chain_l += 3 * t.logical()

    # Optimizer pass: 5x param bytes f32 (grads+params+momentum read,
    # params+momentum write).
    pbytes = PARAM_COUNT * 4
    groups["optimizer (5x params f32)"] = {"logical": 5 * pbytes,
                                           "padded": 5 * pbytes}

    tot_l = sum(g["logical"] for g in groups.values())
    tot_p = sum(g["padded"] for g in groups.values())
    print(f"ResNet-50 v1.5 static traffic model, batch {n} "
          f"(assumptions in module docstring)")
    print(f"{'group':28s} {'logical GB':>11s} {'padded GB':>10s} {'pad x':>6s}")
    for name, g in sorted(groups.items(), key=lambda kv: -kv[1]["padded"]):
        ratio = g["padded"] / g["logical"] if g["logical"] else 0
        print(f"{name:28s} {g['logical']/1e9:11.2f} {g['padded']/1e9:10.2f} "
              f"{ratio:6.2f}")
    print(f"{'TOTAL':28s} {tot_l/1e9:11.2f} {tot_p/1e9:10.2f} "
          f"{tot_p/tot_l:6.2f}")
    print(f"fwd logical {fwd_l/1e9:.2f} GB | bwd logical {bwd_l/1e9:.2f} GB "
          f"| BN-train extra chain {bn_chain_l/1e9:.2f} GB "
          f"(within fwd; the stats+normalize touches)")

    # Variant B: fusion-aware split (see VARIANT_B above).
    bf = bb = 0
    for t in tensors:
        f, b = _variant_b_touches(t)
        bf += f * t.logical()
        bb += b * t.logical()
    pb = groups["optimizer (5x params f32)"]["logical"]
    print(f"variant B (fusion-aware): fwd {bf/1e9:.2f} GB, bwd {bb/1e9:.2f} "
          f"GB, +opt {pb/1e9:.2f} GB, total {(bf+bb+pb)/1e9:.2f} GB "
          f"(measured at 512: fwd-train 38.1, bwd ~105.2, full 143.5)")
    print(json.dumps({"batch": n, "logical_gb": round(tot_l / 1e9, 2),
                      "padded_gb": round(tot_p / 1e9, 2),
                      "fwd_logical_gb": round(fwd_l / 1e9, 2),
                      "bwd_logical_gb": round(bwd_l / 1e9, 2),
                      "bn_chain_gb": round(bn_chain_l / 1e9, 2),
                      "variant_b_fwd_gb": round(bf / 1e9, 2),
                      "variant_b_bwd_gb": round(bb / 1e9, 2),
                      "variant_b_total_gb": round((bf + bb + pb) / 1e9, 2),
                      "measured_gb_batch512": 143.5,
                      "param_count_model": param_count(tensors),
                      "param_count_reference": PARAM_COUNT}))


if __name__ == "__main__":
    main()
