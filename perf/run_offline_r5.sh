#!/bin/bash
# Round-5 OFFLINE queue (no chip needed; AOT flock serializes with any
# concurrent census).  Order:
#   1. regenerate every round-4 offline row that contained a pallas op —
#      they were compiled with the kernels in INTERPRETER mode (XLA while
#      loops, not Mosaic custom calls; see ensure_cpu_backend) and their
#      bytes/memory verdicts describe a program that never runs on chip;
#   2. the new flash-ring capacity rows (ring stages with flash_mha_lse);
#   3. the v4-family re-audit (TOPO=v4:2x2x2; 32 GB HBM — VERDICT r4 #5)
#      of the bench census + the flagship capacity entries.
set -u
cd "$(dirname "$0")/.."
mkdir -p perf/results
LOG=perf/results/run_offline_r5.log
echo "=== run_offline_r5 $(date -u +%FT%TZ) ===" >> "$LOG"
note() { echo "[offline-r5 $(date -u +%T)] $*" | tee -a "$LOG"; }
ENV="PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu"

run() { # name cmd...
  local name=$1; shift
  note "START $name"
  env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu timeout 3600 "$@" \
      > "perf/results/$name.out" 2> "perf/results/$name.err"
  note "END $name rc=$?"
}

# 1. round-4 pallas-row regeneration (now real Mosaic lowering)
run offline_ab_lmxent_r5 python perf/exp_offline_ab.py lm_xent
run offline_ab_lm8k_r5 python perf/exp_offline_ab.py lm_8k
run capacity_ulysses_r5 python perf/exp_capacity_audit.py lm_32k_ulysses

# 2. flash-ring capacity rows (new this round)
run capacity_ring_pallas_r5 python perf/exp_capacity_audit.py lm_32k_ring_pallas
run capacity_ring_pallas_exact_r5 python perf/exp_capacity_audit.py lm_long_exact_pallas

# 3. v4 family re-audit
TOPO=v4:2x2x2 run v4_hlo_b512 env TOPO=v4:2x2x2 B=512 python perf/exp_hlo_offline.py
TOPO=v4:2x2x2 run v4_capacity_all env TOPO=v4:2x2x2 python perf/exp_capacity_audit.py all

note "offline r5 queue complete"
