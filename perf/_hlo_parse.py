"""Side-effect-free HLO text parsing helpers (importable from tests).

Kept separate from ``_common`` (whose ``setup`` path pulls jax config)
and from the experiment scripts (whose import guards re-exec the
process): this module is pure text parsing.
"""

import re


def allreduce_payload(txt: str):
    """Sum all-reduce payload bytes from optimized-HLO text.

    Returns ``({"bf16": bytes, "f32": bytes}, op_count)``.  Handles
    XLA's variadic tuple all-reduces; an ``all-reduce-start``'s result
    tuple aliases the operand (shapes appear twice — the form the
    latency-hiding scheduler emits), so those instructions are halved.
    """
    payload = {"bf16": 0.0, "f32": 0.0}
    ops = 0
    for line in txt.splitlines():
        stripped = line.strip()
        m = re.match(r"%?[\w.-]+ = (.*?) all-reduce(-start)?\(", stripped)
        if not m:
            continue
        factor = 0.5 if m.group(2) else 1.0
        for dt, dims in re.findall(r"(bf16|f32)\[([0-9,]*)\]", m.group(1)):
            sz = {"bf16": 2, "f32": 4}[dt]
            k = 1
            for d in dims.split(","):
                if d:
                    k *= int(d)
            payload[dt] += k * sz * factor
        ops += 1
    return payload, ops
