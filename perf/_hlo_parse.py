"""Compatibility shim — the parser was promoted into the framework.

``allreduce_payload`` (and the general collective parser that replaced
its regex) now live in ``tpuframe.analysis.hlo_audit``; this module
keeps the historical ``from _hlo_parse import allreduce_payload`` import
path of the perf scripts working.

Loaded by file path rather than ``import tpuframe...`` on purpose: the
``tpuframe`` package __init__ imports jax, and this module's contract is
*side-effect-free text parsing* — several perf scripts import it before
their env-guard re-exec, when initializing jax would pin the wrong
backend.  ``hlo_audit`` itself imports nothing but the stdlib.
"""

import importlib.util
import os
import sys

_HLO_AUDIT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tpuframe", "analysis", "hlo_audit.py")

if "tpuframe.analysis.hlo_audit" in sys.modules:
    _mod = sys.modules["tpuframe.analysis.hlo_audit"]
else:
    _spec = importlib.util.spec_from_file_location(
        "_hlo_parse_impl", _HLO_AUDIT)
    _mod = importlib.util.module_from_spec(_spec)
    sys.modules["_hlo_parse_impl"] = _mod  # dataclasses resolve via here
    _spec.loader.exec_module(_mod)

allreduce_payload = _mod.allreduce_payload
parse_collectives = _mod.parse_collectives

# The collective-flow graph parser (analysis v2) rides the same shim:
# still pure text parsing, still loadable before any backend decision.
_COLLECTIVE_GRAPH = os.path.join(os.path.dirname(_HLO_AUDIT),
                                 "collective_graph.py")

if "tpuframe.analysis.collective_graph" in sys.modules:
    _graph_mod = sys.modules["tpuframe.analysis.collective_graph"]
else:
    sys.modules.setdefault("_hlo_parse_impl", _mod)
    _gspec = importlib.util.spec_from_file_location(
        "_collective_graph_impl", _COLLECTIVE_GRAPH)
    _graph_mod = importlib.util.module_from_spec(_gspec)
    sys.modules["_collective_graph_impl"] = _graph_mod
    _gspec.loader.exec_module(_graph_mod)

parse_graph = _graph_mod.parse_graph
CollectiveGraph = _graph_mod.CollectiveGraph
