"""Dump the compiled ResNet-50 train step's optimized HLO + memory analysis.

The step is HBM-bound (perf/exp_breakdown.py: 143.5 GB accessed/step at
batch 512 = 280 MB/image vs a ~45 GB naive activation-traffic estimate, and
t_hbm = 177 ms vs 218 ms measured).  This dumps what the compiler actually
laid out so the byte inflation can be attributed — prime suspect: lane
padding (feature dims < 128 stored as 128-wide), which multiplies traffic
for C=3 inputs and C=64 stem tensors.

Writes perf/results/resnet_step_hlo.txt (optimized HLO with layouts) and
prints memory_analysis + the largest allocations.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import make_log, setup

jax = setup()
import re

import jax.numpy as jnp
import numpy as np
import optax

from tpuframe import models
from tpuframe.models import losses
from tpuframe.parallel import step as step_lib

# 256 is the measured throughput optimum (BASELINE.md round 3) and half the
# compile surface of 512 — the byte ATTRIBUTION (which tensors inflate) is
# batch-proportional either way.  Override with B=512 for the exact
# roofline-measurement shape.
BATCH = int(os.environ.get("B", "256"))
log = make_log("hlo-dump")


def main():
    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    log(f"building host batch (B={BATCH})...")
    x_host = rng.normal(0.5, 0.25, size=(BATCH, 224, 224, 3)).astype(np.float32)
    log("transferring to device...")
    x = jnp.asarray(x_host, jnp.bfloat16)
    y = jnp.asarray(rng.integers(0, 1000, size=(BATCH,)), jnp.int32)
    jax.block_until_ready(x)
    log("init model params (device)...")
    variables = model.init(jax.random.key(0), x[:2])
    jax.block_until_ready(variables)
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    state = step_lib.TrainState.create(
        variables["params"], tx,
        model_state={"batch_stats": variables["batch_stats"]})
    train_step = step_lib.make_train_step(loss_fn, tx, None, donate=False)
    batch = {"image": x, "label": y}

    log("lower+compile...")
    compiled = train_step.lower(state, batch).compile()

    try:
        ma = compiled.memory_analysis()
        log(f"memory: argument={ma.argument_size_in_bytes/1e9:.2f}GB "
            f"output={ma.output_size_in_bytes/1e9:.2f}GB "
            f"temp={ma.temp_size_in_bytes/1e9:.2f}GB "
            f"peak={getattr(ma, 'peak_memory_in_bytes', 0)/1e9:.2f}GB")
    except Exception as e:  # noqa: BLE001
        log(f"memory_analysis unavailable: {e}")

    txt = compiled.as_text()
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "results", "resnet_step_hlo.txt")
    with open(out_path, "w") as f:
        f.write(txt)
    log(f"wrote {out_path} ({len(txt)/1e6:.1f} MB)")

    # Quick shape census: total padded vs logical bytes per dtype-shape
    # (helpers shared with exp_hlo_offline via _common).
    from _common import hlo_shape_census, hlo_nbytes

    log("top shapes by total bytes (count x padded-est):")
    for k, n in hlo_shape_census(txt)[:25]:
        log(f"  {n:5d} x {k}  ~{hlo_nbytes(k)/1e6:.1f} MB each")


if __name__ == "__main__":
    main()
