"""Dump the compiled ResNet-50 train step's optimized HLO + memory analysis.

The step is HBM-bound (perf/exp_breakdown.py: 143.5 GB accessed/step at
batch 512 = 280 MB/image vs a ~45 GB naive activation-traffic estimate, and
t_hbm = 177 ms vs 218 ms measured).  This dumps what the compiler actually
laid out so the byte inflation can be attributed — prime suspect: lane
padding (feature dims < 128 stored as 128-wide), which multiplies traffic
for C=3 inputs and C=64 stem tensors.

Writes perf/results/resnet_step_hlo.txt (optimized HLO with layouts) and
prints memory_analysis + the largest allocations.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _common import make_log, setup

jax = setup()
import re

import jax.numpy as jnp
import numpy as np
import optax

from tpuframe import models
from tpuframe.models import losses
from tpuframe.parallel import step as step_lib

# 256 is the measured throughput optimum (BASELINE.md round 3) and half the
# compile surface of 512 — the byte ATTRIBUTION (which tensors inflate) is
# batch-proportional either way.  Override with B=512 for the exact
# roofline-measurement shape.
BATCH = int(os.environ.get("B", "256"))
log = make_log("hlo-dump")


def main():
    model = models.ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    log(f"building host batch (B={BATCH})...")
    x_host = rng.normal(0.5, 0.25, size=(BATCH, 224, 224, 3)).astype(np.float32)
    log("transferring to device...")
    x = jnp.asarray(x_host, jnp.bfloat16)
    y = jnp.asarray(rng.integers(0, 1000, size=(BATCH,)), jnp.int32)
    jax.block_until_ready(x)
    log("init model params (device)...")
    variables = model.init(jax.random.key(0), x[:2])
    jax.block_until_ready(variables)
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)

    def loss_fn(params, model_state, batch, step_rng):
        logits, mutated = model.apply(
            {"params": params, **model_state}, batch["image"], train=True,
            mutable=["batch_stats"])
        loss = losses.softmax_cross_entropy(logits, batch["label"],
                                            label_smoothing=0.1)
        return loss, (dict(mutated), {})

    state = step_lib.TrainState.create(
        variables["params"], tx,
        model_state={"batch_stats": variables["batch_stats"]})
    train_step = step_lib.make_train_step(loss_fn, tx, None, donate=False)
    batch = {"image": x, "label": y}

    log("lower+compile...")
    compiled = train_step.lower(state, batch).compile()

    try:
        ma = compiled.memory_analysis()
        log(f"memory: argument={ma.argument_size_in_bytes/1e9:.2f}GB "
            f"output={ma.output_size_in_bytes/1e9:.2f}GB "
            f"temp={ma.temp_size_in_bytes/1e9:.2f}GB "
            f"peak={getattr(ma, 'peak_memory_in_bytes', 0)/1e9:.2f}GB")
    except Exception as e:  # noqa: BLE001
        log(f"memory_analysis unavailable: {e}")

    txt = compiled.as_text()
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "results", "resnet_step_hlo.txt")
    with open(out_path, "w") as f:
        f.write(txt)
    log(f"wrote {out_path} ({len(txt)/1e6:.1f} MB)")

    # Quick shape census: total padded vs logical bytes per dtype-shape.
    # TPU layouts appear as e.g. bf16[512,112,112,64]{3,2,1,0:T(8,128)(2,1)}.
    shapes = re.findall(r"(bf16|f32|s32|pred)\[([0-9,]*)\]\{([^}]*)\}", txt)
    census: dict = {}
    for dt, dims, layout in shapes:
        key = f"{dt}[{dims}]{{{layout}}}"
        census[key] = census.get(key, 0) + 1
    big = sorted(census.items(),
                 key=lambda kv: -_nbytes(kv[0]) * kv[1])[:25]
    log("top shapes by total bytes (count x padded-est):")
    for k, n in big:
        log(f"  {n:5d} x {k}  ~{_nbytes(k)/1e6:.1f} MB each")


def _nbytes(key: str) -> float:
    m = re.match(r"(bf16|f32|s32|pred)\[([0-9,]*)\]\{([^:}]*)", key)
    if not m:
        return 0.0
    dt, dims, perm = m.groups()
    if not dims:
        return 0.0
    sz = {"bf16": 2, "f32": 4, "s32": 4, "pred": 1}[dt]
    parts = [int(d) for d in dims.split(",") if d]
    if not parts:
        return 0.0
    # The layout's minor-to-major list says which LOGICAL dim is physically
    # minor — that dim gets the 128-lane rounding, the next-minor the
    # 8-sublane rounding.  Falling back to logical order when unparsable.
    try:
        mtm = [int(p) for p in perm.split(",") if p.strip() != ""]
    except ValueError:
        mtm = []
    if len(mtm) != len(parts):
        mtm = list(range(len(parts) - 1, -1, -1))
    padded = list(parts)
    if mtm:
        minor = mtm[0]
        padded[minor] = (padded[minor] + 127) // 128 * 128
        if len(mtm) > 1:
            nxt = mtm[1]
            padded[nxt] = (padded[nxt] + 7) // 8 * 8
    n = 1.0
    for d in padded:
        n *= d
    return n * sz


if __name__ == "__main__":
    main()
